"""jit-purity: host side effects inside traced function bodies.

``jax.jit``/``lax.scan``/``vmap`` TRACE the Python function once per
shape signature, then replay the compiled program. A side effect in the
body (mutating a global or attribute, recording a metric, ``print``,
reading ``time.time``) executes at trace time only — silently absent on
every subsequent call, or worse, it captures a tracer. A metrics
``record_*`` call in a decode body records exactly one sample per
compile, which reads as "decode ran once" on the dashboard while the
chip serves millions of steps.

Pass 1 collects the module's traced functions: defs decorated with
``jax.jit`` / ``partial(jax.jit, ...)`` / ``jax.vmap`` / ``jax.pmap``,
names passed to ``jax.jit(f)`` / ``vmap(f)`` / ``pmap(f)`` /
``shard_map(f, ...)``, and bodies handed to ``lax.scan`` /
``lax.fori_loop`` / ``lax.while_loop`` / ``lax.map``. Pass 2 flags, in
each traced body (nested defs included — they trace too):

* ``global`` / ``nonlocal`` declarations
* assignments to attributes (``obj.attr = ...``, ``+=`` included)
* ``print(...)``
* ``time.time/perf_counter/monotonic`` and ``datetime.now``
* metric recording: calls whose terminal name starts with ``record_`` or
  ``observe_``, or metric-object methods ``.inc()`` / ``.observe()``
  (``.set()`` is exempt — ``x.at[i].set(v)`` is the functional-update
  idiom, not a side effect)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from tools.graftlint.core import Finding, Module, Project, dotted, make_finding

RULE = "jit-purity"

TRACER_WRAPPERS = {"jax.jit", "jit", "jax.vmap", "vmap", "jax.pmap", "pmap",
                   "shard_map", "jax.shard_map", "pjit", "jax.pjit"}
# wrapper -> argument positions holding the traced body
BODY_ARG_POSITIONS = {
    "lax.scan": (0,), "jax.lax.scan": (0,),
    "lax.map": (0,), "jax.lax.map": (0,),
    "lax.fori_loop": (2,), "jax.lax.fori_loop": (2,),
    "lax.while_loop": (0, 1), "jax.lax.while_loop": (0, 1),
    "lax.cond": (1, 2), "jax.lax.cond": (1, 2),
}

TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
              "datetime.now", "datetime.datetime.now", "datetime.utcnow"}


def _is_tracer_wrapper(func: ast.AST) -> bool:
    d = dotted(func)
    if d in TRACER_WRAPPERS:
        return True
    # partial(jax.jit, ...) used as decorator or factory
    if isinstance(func, ast.Call):
        name = dotted(func.func) or ""
        if name in ("partial", "functools.partial") and func.args:
            return (dotted(func.args[0]) or "") in TRACER_WRAPPERS
        return name in TRACER_WRAPPERS
    return False


def _collect_traced(tree: ast.Module):
    """(traced function-def nodes, traced lambda nodes)."""
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    traced: Set[int] = set()
    traced_nodes = []

    def mark(fnode: ast.AST):
        if isinstance(fnode, ast.Lambda):
            if id(fnode) not in traced:
                traced.add(id(fnode))
                traced_nodes.append(fnode)
        else:
            name = dotted(fnode)
            target = defs.get(name) if name else None
            if target is not None and id(target) not in traced:
                traced.add(id(target))
                traced_nodes.append(target)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_tracer_wrapper(dec):
                    if id(node) not in traced:
                        traced.add(id(node))
                        traced_nodes.append(node)
        elif isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if name in TRACER_WRAPPERS or _is_tracer_wrapper(node.func):
                if node.args:
                    mark(node.args[0])
            elif name in BODY_ARG_POSITIONS:
                for p in BODY_ARG_POSITIONS[name]:
                    if p < len(node.args):
                        mark(node.args[p])
    return traced_nodes


class JitPurityChecker:
    rule = RULE

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            for fnode in _collect_traced(module.tree):
                name = getattr(fnode, "name", "<lambda>")
                body = fnode.body if isinstance(fnode.body, list) else [
                    ast.Expr(value=fnode.body)]
                for stmt in body:
                    self._check(stmt, module, name, findings)
        return findings

    def _check(self, node, module: Module, qualname: str, findings):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(sub, ast.Global) else "nonlocal"
                findings.append(make_finding(
                    module, RULE, sub,
                    f"'{kind} {', '.join(sub.names)}' inside traced function "
                    f"{qualname!r}: the mutation runs once at TRACE time, "
                    "not per call — the compiled program never sees it.",
                    qualname))
            elif isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        findings.append(make_finding(
                            module, RULE, t,
                            f"attribute mutation '{dotted(t) or t.attr} = ...' "
                            f"inside traced function {qualname!r}: executes "
                            "at trace time only (and may capture a tracer "
                            "into host state). Return the value instead.",
                            qualname))
            elif isinstance(sub, ast.Call):
                d = dotted(sub.func) or ""
                term = sub.func.attr if isinstance(sub.func, ast.Attribute) \
                    else (sub.func.id if isinstance(sub.func, ast.Name) else "")
                if term == "print" or d == "print":
                    findings.append(make_finding(
                        module, RULE, sub,
                        f"print() inside traced function {qualname!r}: fires "
                        "once per compile, not per call — use jax.debug.print "
                        "for traced values.", qualname))
                elif d in TIME_CALLS:
                    findings.append(make_finding(
                        module, RULE, sub,
                        f"{d}() inside traced function {qualname!r}: reads "
                        "the clock at TRACE time and bakes the constant into "
                        "the compiled program. Time on the host, around the "
                        "call.", qualname))
                elif term.startswith(("record_", "observe_")) or (
                        isinstance(sub.func, ast.Attribute)
                        and term in ("inc", "observe")):
                    findings.append(make_finding(
                        module, RULE, sub,
                        f"metrics call '{d or term}()' inside traced function "
                        f"{qualname!r}: records one sample per COMPILE, not "
                        "per step — the series silently flatlines. Record "
                        "from the host loop around the jit.", qualname))
