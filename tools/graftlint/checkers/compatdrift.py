"""compat-drift: version-sensitive JAX APIs must route through the shim.

The PR 4 postmortem class: ``shard_map`` moved twice under this tree
(``jax.experimental.shard_map.shard_map`` -> ``jax.shard_map``, renaming
``check_rep`` to ``check_vma`` on the way) and ``jax.lax.axis_size`` only
exists on newer releases. Five ring-attention tests sat red for a whole
round because one module imported the old path directly. The resolution
lives in exactly one place — ``parallel/compat.py`` — and this checker
makes the shim impossible to bypass: any direct import or dotted use of
the moved APIs outside the shim file is a finding.

Flagged anywhere in the scanned tree (not just hot dirs — version drift
breaks cold paths just as hard):

- ``from jax.experimental.shard_map import ...`` / ``import
  jax.experimental.shard_map``
- ``from jax import shard_map`` / ``jax.shard_map(...)`` /
  ``jax.experimental.shard_map.shard_map(...)``
- ``from jax.lax import axis_size`` / ``jax.lax.axis_size(...)`` /
  ``lax.axis_size(...)``
"""

from __future__ import annotations

import ast
from typing import List

from tools.graftlint.core import Finding, Module, Project, dotted, make_finding

RULE = "compat-drift"

SHIM = "parallel/compat.py"

# dotted names whose appearance (call or bare reference) is drift
_BANNED_DOTTED = {
    "jax.shard_map": "shard_map",
    "jax.experimental.shard_map": "shard_map",
    "jax.experimental.shard_map.shard_map": "shard_map",
    "jax.lax.axis_size": "axis_size",
    "lax.axis_size": "axis_size",
}

_MSG = {
    "shard_map": (
        "direct shard_map use bypasses the version shim — the API moved "
        "twice (jax.experimental.shard_map -> jax.shard_map, check_rep -> "
        "check_vma); import it from seldon_core_tpu.parallel.compat instead"
    ),
    "axis_size": (
        "jax.lax.axis_size only exists on newer JAX — use "
        "seldon_core_tpu.parallel.compat.axis_size (psum(1, axis) fallback) "
        "instead"
    ),
}


def _is_shim(module: Module) -> bool:
    return module.relpath.replace("\\", "/").endswith(SHIM)


class CompatDriftChecker:
    rule = RULE

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            if _is_shim(module):
                continue
            findings.extend(self._check_module(module))
        return findings

    def _check_module(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        seen = set()

        def flag(node, api: str, function: str = ""):
            key = (getattr(node, "lineno", 0), api)
            if key in seen:
                return
            seen.add(key)
            findings.append(make_finding(module, RULE, node, _MSG[api], function))

        # imports (module level or nested — graftlint reports the line)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                mod = node.module
                names = {a.name for a in node.names}
                if mod == "jax.experimental.shard_map" or (
                        mod in ("jax", "jax.experimental") and "shard_map" in names):
                    flag(node, "shard_map")
                if mod == "jax.lax" and "axis_size" in names:
                    flag(node, "axis_size")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.experimental.shard_map":
                        flag(node, "shard_map")
            else:
                d = dotted(node)
                if d in _BANNED_DOTTED:
                    flag(node, _BANNED_DOTTED[d])
        return findings
