"""host-sync-in-hot-path: device->host synchronization on the serving path.

The PR 3 postmortem class: one ``np.asarray(nxt)`` inside the continuous
batcher's decode loop serialized host and device and served 7B at 11% of
direct decode. Any call that materializes a device value on the host
(``np.asarray``/``np.array``/``jax.device_get``/``.block_until_ready()``,
and ``float()/int()/bool()/.item()`` applied to device values) blocks the
Python thread until the device stream drains — on the decode path that
is a full pipeline stall per token.

Scope: files under the hot-path packages (``runtime/``, ``servers/``,
``ops/``, ``transport/``), plus the frame codec (``codec/framing*.py``) —
see "Framing egress" below. Within the hot packages a finding fires when

* a STRONG sync call (np.asarray / np.array / jax.device_get /
  .block_until_ready()) appears inside a hot-named function (decode /
  prefill / extend / generate / predict / step / drain / dispatch /
  sample / forward / attention / transform — the serving verbs), OR its
  argument is device-tainted anywhere in a hot-path file;
* a WEAK sync call (float / int / bool / .item()) has a device-tainted
  argument (these four are pervasive on host values, so the bare
  hot-function heuristic would drown the signal).

Framing egress (PR 18): the frame codec's contract is ONE bulk
device->host transfer per frame — raw-buffer assembly must never sync
device arrays per-tensor (each per-leaf ``np.asarray``/``.item()`` in the
pack loop is a full host/device serialization, the PR 3 stall class at
frame-encode time). In framing files a STRONG sync (or a bare
``.item()``) fires whenever it sits inside a loop — loop depth stands in
for hot-function naming, since every per-tensor iteration is the bug —
and device-tainted arguments fire anywhere, exactly as in hot files. The
single legitimate bulk ``jax.device_get`` sits outside any loop and
carries the mandatory inline suppression telling that story.

Device taint is a per-function, statement-ordered dataflow: an expression
is device-valued when it mentions ``jnp.*``/``jax.*``/``lax.*``, calls a
function whose name carries a device verb (jit/decode/prefill/extend/
step/apply/scan/vmap/pmap/sample/matmul/kernel/forward), or reads a name
previously assigned from such an expression. A top-level ``np.*`` call
launders taint — its result already lives on the host.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from tools.graftlint.core import Finding, Module, Project, dotted, make_finding

RULE = "host-sync-in-hot-path"

HOT_DIRS = ("runtime", "servers", "ops", "transport")

HOT_FN_RE = re.compile(
    r"(decode|prefill|extend|generate|predict|step|drain|dispatch|sample"
    r"|forward|attention|transform)", re.IGNORECASE)

DEVICE_FN_RE = re.compile(
    r"(jit|decode|prefill|extend|step|apply|scan|vmap|pmap|sample|matmul"
    r"|kernel|forward)", re.IGNORECASE)

# bare .decode()/.encode() are bytes/str/tokenizer methods (host), not the
# decode-step device verb — only COMPOUND names (decode_step, _get_decode)
# count as device producers
HOST_METHOD_TERMINALS = {"decode", "encode"}

STRONG_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "jax.device_get"}
WEAK_BUILTINS = {"float", "int", "bool"}
DEVICE_ROOTS = ("jnp", "jax", "lax")


def _is_hot_file(module: Module) -> bool:
    return any(p in HOT_DIRS for p in module.parts[:-1])


def _is_framing_file(module: Module) -> bool:
    """The frame codec: codec/framing*.py (tensorproto and the other codec
    modules keep the hot-package scoping — their ndarray round trips are
    the JSON path's job, not frame assembly)."""
    return ("codec" in module.parts[:-1]
            and "fram" in module.parts[-1])


def _terminal_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


HOST_BUILTINS = {"int", "float", "bool", "str", "len", "list", "tuple", "range"}


def _call_root(call: ast.Call) -> str:
    """Root module of a (possibly method-chained) call: the base of
    ``np.asarray(x).astype(y)`` is ``np``."""
    func = call.func
    while isinstance(func, ast.Attribute):
        if isinstance(func.value, ast.Call):
            return _call_root(func.value)
        func = func.value
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _launders(value: ast.AST) -> bool:
    """True when ``value`` is a call whose result is a HOST value no matter
    what went in: np.*/numpy.* (asarray pulls the device value over) and
    the scalar builtins."""
    if not isinstance(value, ast.Call):
        return False
    root = _call_root(value)
    return root in ("np", "numpy") or root in HOST_BUILTINS


class _Taint:
    """Per-function device-taint state over dotted names."""

    def __init__(self, tainted: Optional[Set[str]] = None):
        self.names: Set[str] = set(tainted or ())

    def expr_is_device(self, node: ast.AST) -> bool:
        """Recursive walk that stops at laundering calls: anything beneath
        an np.*/builtin call already got synced there, so its RESULT is a
        host value for the purposes of the enclosing expression."""
        if isinstance(node, ast.Call):
            if _launders(node):
                return False
            name = _terminal_name(node.func)
            if name and name not in HOST_METHOD_TERMINALS \
                    and DEVICE_FN_RE.search(name):
                return True
        d = dotted(node)
        if d is not None:
            if d in self.names:
                return True
            root = d.split(".", 1)[0]
            if root in DEVICE_ROOTS and "." in d:
                return True
            if isinstance(node, (ast.Name, ast.Attribute)):
                return False  # a clean dotted chain; no deeper structure
        return any(self.expr_is_device(c) for c in ast.iter_child_nodes(node))

    def _outermost_targets(self, t: ast.AST):
        """Yield the dotted names an assignment target rebinds — only the
        OUTERMOST chains (``self._rng, key = ...`` rebinds ``self._rng``
        and ``key``, never bare ``self``)."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                yield from self._outermost_targets(elt)
        elif isinstance(t, ast.Starred):
            yield from self._outermost_targets(t.value)
        elif isinstance(t, ast.Subscript):
            d = dotted(t.value)
            if d is not None:
                yield d
        else:
            d = dotted(t)
            if d is not None:
                yield d

    def assign(self, targets: List[ast.AST], value: Optional[ast.AST]):
        device = value is not None and not _launders(value) \
            and self.expr_is_device(value)
        for t in targets:
            for d in self._outermost_targets(t):
                if device:
                    self.names.add(d)
                else:
                    self.names.discard(d)


def _own_nodes(stmt: ast.stmt):
    """The expressions belonging to THIS statement — compound bodies are
    handled by the block recursion, which sees the correctly-ordered taint
    state (walking them early would apply pre-block taint to in-block
    code and flag values laundered to host inside the block)."""
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target, stmt.iter]
    if isinstance(stmt, (ast.While, ast.If)):
        return [stmt.test]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        nodes = [i.context_expr for i in stmt.items]
        nodes += [i.optional_vars for i in stmt.items if i.optional_vars]
        return nodes
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _sync_calls(stmt: ast.stmt):
    """(call, kind, subject) for every sync-inducing call in the
    statement's OWN expressions (see _own_nodes). kind is 'strong' |
    'weak'; subject is the expression whose deviceness matters (the
    argument, or the receiver for methods)."""
    for root in _own_nodes(stmt):
        yield from _sync_calls_in(root)


def _sync_calls_in(root: ast.AST):
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func) or ""
        term = _terminal_name(node.func)
        if d in STRONG_FUNCS:
            yield node, "strong", (node.args[0] if node.args else None)
        elif term == "block_until_ready" and isinstance(node.func, ast.Attribute):
            yield node, "strong", node.func.value
        elif term == "item" and isinstance(node.func, ast.Attribute) and not node.args:
            yield node, "weak", node.func.value
        elif isinstance(node.func, ast.Name) and node.func.id in WEAK_BUILTINS \
                and len(node.args) == 1:
            yield node, "weak", node.args[0]


class HostSyncChecker:
    rule = RULE

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            framing = _is_framing_file(module)
            if not (_is_hot_file(module) or framing):
                continue
            findings.extend(self._check_module(module, framing))
        return findings

    def _check_module(self, module: Module,
                      framing: bool = False) -> List[Finding]:
        findings: List[Finding] = []
        seen = set()  # (line, kind) — one finding per sync site

        def check_function(fn, qualname: str, hot_stack: bool):
            hot = hot_stack or bool(HOT_FN_RE.search(fn.name))
            taint = _Taint()
            self._walk_block(fn.body, module, qualname, hot, taint,
                             findings, seen, check_function,
                             framing=framing)

        for node in module.tree.body:
            self._top_level(node, module, findings, seen, check_function, "")
        return findings

    def _top_level(self, node, module, findings, seen, check_function, prefix):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            q = f"{prefix}.{node.name}" if prefix else node.name
            check_function(node, q, False)
        elif isinstance(node, ast.ClassDef):
            q = f"{prefix}.{node.name}" if prefix else node.name
            for child in node.body:
                self._top_level(child, module, findings, seen, check_function, q)

    def _walk_block(self, stmts, module, qualname, hot, taint, findings,
                    seen, check_function, framing=False, loops=0):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: inherits hotness, fresh taint scope (and a
                # fresh loop depth — its body runs per CALL, not per
                # enclosing iteration)
                q = f"{qualname}.{stmt.name}"
                nested_hot = hot or bool(HOT_FN_RE.search(stmt.name))
                inner = _Taint()
                self._walk_block(stmt.body, module, q, nested_hot, inner,
                                 findings, seen, check_function,
                                 framing=framing)
                continue
            self._check_stmt(stmt, module, qualname, hot, taint, findings,
                             seen, framing=framing, loops=loops)
            # descend into compound statements with the same taint scope;
            # a loop's BODY bumps the depth the framing-egress arm keys on
            # (orelse runs once, after the loop — it stays at this depth)
            is_loop = isinstance(stmt, (ast.For, ast.AsyncFor, ast.While))
            for attr in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, attr, None)
                if inner:
                    self._walk_block(inner, module, qualname, hot, taint,
                                     findings, seen, check_function,
                                     framing=framing,
                                     loops=loops + (1 if is_loop
                                                    and attr == "body"
                                                    else 0))
            for handler in getattr(stmt, "handlers", []) or []:
                self._walk_block(handler.body, module, qualname, hot, taint,
                                 findings, seen, check_function,
                                 framing=framing, loops=loops)

    def _check_stmt(self, stmt, module, qualname, hot, taint, findings,
                    seen, framing=False, loops=0):
        # flag first (against taint state BEFORE this statement's bindings)
        for call, kind, subject in _sync_calls(stmt):
            device = subject is not None and taint.expr_is_device(subject)
            if framing:
                # framing egress: per-tensor assembly loops are the bug —
                # a strong sync (or bare .item()) per iteration serializes
                # host and device once per LEAF instead of once per frame
                in_loop = loops > 0 and (
                    kind == "strong"
                    or _terminal_name(call.func) == "item")
                fire = device or in_loop
            else:
                fire = device or (kind == "strong" and hot)
            if not fire:
                continue
            key = (call.lineno, kind, ast.dump(call.func))
            if key in seen:
                continue
            seen.add(key)
            what = dotted(call.func) or _terminal_name(call.func)
            if device:
                why = "device-valued argument"
            elif framing:
                why = (f"inside a loop in frame codec function {qualname!r}"
                       " — frame assembly owes ONE bulk transfer per frame,"
                       " not one sync per tensor")
            else:
                why = f"inside hot-path function {qualname!r}"
            findings.append(make_finding(
                module, RULE, call,
                f"{what}() forces a device->host sync ({why}); on the "
                "serving path this blocks until the device stream drains "
                "(the PR 3 decode-loop stall class). Move it off the hot "
                "path, keep the value device-resident, or annotate why "
                "this sync is deliberate.",
                qualname))
        # then update taint from this statement's own bindings
        if isinstance(stmt, ast.Assign):
            taint.assign(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            taint.assign([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            taint.assign([stmt.target], stmt.value)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            taint.assign([stmt.target], stmt.iter)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    taint.assign([item.optional_vars], item.context_expr)
