"""use-after-donate: reading a buffer after XLA was told it may reuse it.

The PR 2 hazard class: ``jax.jit(..., donate_argnums=...)`` lets XLA alias
an input buffer into the output (in-place KV-cache updates at serving
scale depend on it), but the Python name still points at the now-invalid
buffer. Reading it afterwards is undefined — on CPU it often *works*,
then corrupts silently on TPU where the aliasing actually fires. Every
``donate_argnums`` site had to be hand-audited in PR 2; this checker is
that audit, mechanized.

Per-module pass 1 collects donating callables:

* ``name = jax.jit(f, donate_argnums=(0, 2))`` (also ``self.attr = ...``)
* ``@partial(jax.jit, donate_argnums=(1,))`` / ``@jax.jit(donate_argnums=…)``
  decorated defs
* inline ``jax.jit(f, donate_argnums=…)(args…)`` calls

Per-function pass 2 is a statement-ordered walk: a plain-name (or dotted
``self.attr``) argument at a donated position becomes DEAD at the call;
any later read before a rebind is a finding. A rebind on the same
statement (``caches = step(caches)`` — the threading idiom) clears the
taint, so the canonical donate-and-rethread pattern is clean. A loop whose
body donates a name without rebinding it is flagged at the donation site:
iteration 2 would feed a dead buffer back into the jit.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.graftlint.core import Finding, Module, Project, dotted, make_finding

RULE = "use-after-donate"

JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _donate_positions(call: ast.Call) -> Optional[FrozenSet[int]]:
    """Donated argnums from a jax.jit(...) call node, None if not donating."""
    if (dotted(call.func) or "") not in JIT_NAMES:
        return None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return frozenset([v.value])
            if isinstance(v, (ast.Tuple, ast.List)):
                nums = set()
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                        nums.add(elt.value)
                return frozenset(nums)
            return frozenset()  # dynamic donate spec: positions unknown
    return None


def _partial_jit_donations(call: ast.Call) -> Optional[FrozenSet[int]]:
    """Donations from ``partial(jax.jit, donate_argnums=...)``."""
    name = dotted(call.func) or ""
    if name not in ("partial", "functools.partial"):
        return None
    if not call.args or (dotted(call.args[0]) or "") not in JIT_NAMES:
        return None
    return _donate_positions(ast.Call(func=call.args[0], args=[],
                                      keywords=call.keywords)) or frozenset()


def _collect_donators(tree: ast.Module) -> Dict[str, FrozenSet[int]]:
    """dotted-name (terminal form) -> donated positions.

    Attribute targets are keyed by their terminal attr (``self._insert`` and
    ``batcher._insert`` both hit key ``._insert``) — a heuristic, but
    donation-site names are distinctive in practice.
    """
    table: Dict[str, FrozenSet[int]] = {}

    def record(target: ast.AST, positions: FrozenSet[int]):
        d = dotted(target)
        if d is None:
            return
        if "." in d:
            table["." + d.rsplit(".", 1)[1]] = positions
        else:
            table[d] = positions

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            value = node.value
            pos = _donate_positions(value) if isinstance(value, ast.Call) else None
            if pos:
                for t in node.targets:
                    record(t, pos)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                pos = _donate_positions(dec)
                if pos is None:
                    pos = _partial_jit_donations(dec)
                if pos:
                    table[node.name] = pos
                    table["." + node.name] = pos
    return table


def _lookup(table: Dict[str, FrozenSet[int]], func: ast.AST) -> Optional[FrozenSet[int]]:
    d = dotted(func)
    if d is None:
        return None
    if d in table:
        return table[d]
    if "." in d:
        return table.get("." + d.rsplit(".", 1)[1])
    return None


class DonationChecker:
    rule = RULE

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            donators = _collect_donators(module.tree)
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._check_function(node, module, donators, findings)
        return findings

    # ------------------------------------------------------------------
    @staticmethod
    def _own_nodes(stmt) -> List[ast.AST]:
        """The expressions belonging to THIS statement — compound bodies
        are walked by the recursion, not here (walking them early would
        apply pre-loop state to in-loop code)."""
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.target, stmt.iter]
        if isinstance(stmt, (ast.While, ast.If)):
            return [stmt.test]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            nodes: List[ast.AST] = [i.context_expr for i in stmt.items]
            nodes += [i.optional_vars for i in stmt.items if i.optional_vars]
            return nodes
        if isinstance(stmt, ast.Try):
            return []
        return [stmt]

    def _check_function(self, fn, module: Module, donators, findings):
        # dead: dotted name -> (donated_to, at_line)
        dead: Dict[str, Tuple[str, int]] = {}

        def own_walk(stmt):
            for root in self._own_nodes(stmt):
                yield from ast.walk(root)

        def donations_in(stmt) -> List[Tuple[ast.Call, str, List[str]]]:
            out = []
            for node in own_walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                pos = _lookup(donators, node.func)
                if pos is None:
                    inline = (_donate_positions(node.func)
                              if isinstance(node.func, ast.Call) else None)
                    if not inline:
                        continue
                    pos = inline
                names = []
                for p in sorted(pos):
                    if p < len(node.args):
                        d = dotted(node.args[p])
                        if d is not None:
                            names.append(d)
                if names:
                    out.append((node, dotted(node.func) or "<jit>", names))
            return out

        def reads_in(stmt, skip_args: Set[int]) -> List[Tuple[str, ast.AST]]:
            out = []
            for node in own_walk(stmt):
                if not isinstance(getattr(node, "ctx", None), ast.Load):
                    continue  # Store/Del targets are rebinds, not reads
                d = dotted(node)
                if d is not None and d in dead and id(node) not in skip_args:
                    out.append((d, node))
            return out

        def binds_in(stmt) -> List[str]:
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                targets = [stmt.target]
            out = []
            for t in targets:
                for node in ast.walk(t):
                    d = dotted(node)
                    if d is not None:
                        out.append(d)
            return out

        def walk(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # separate scope, analyzed on its own
                donations = donations_in(stmt)
                donated_arg_ids: Set[int] = set()
                for call, _, _ in donations:
                    pos = _lookup(donators, call.func) or frozenset()
                    for p in pos:
                        if p < len(call.args):
                            for sub in ast.walk(call.args[p]):
                                donated_arg_ids.add(id(sub))
                # 1) reads of already-dead names (the donating call's own
                #    donated args are exempt — that's the donation itself)
                for name, node in reads_in(stmt, donated_arg_ids):
                    to, at = dead[name]
                    findings.append(make_finding(
                        module, RULE, node,
                        f"{name!r} is read here but was donated to {to}() at "
                        f"line {at}: donate_argnums lets XLA reuse the buffer, "
                        "so this read is undefined once aliasing fires "
                        "(the PR 2 use-after-donate class). Rebind the name "
                        "from the call's output or drop the donation.",
                        fn.name))
                    del dead[name]  # one finding per donation event
                # 2) new donations
                for call, to, names in donations:
                    for name in names:
                        dead[name] = (to, call.lineno)
                # 3) rebinds clear the taint
                for name in binds_in(stmt):
                    dead.pop(name, None)
                # recurse
                is_loop = isinstance(stmt, (ast.For, ast.AsyncFor, ast.While))
                before = dict(dead)
                for attr in ("body", "orelse", "finalbody"):
                    inner = getattr(stmt, attr, None)
                    if inner:
                        walk(inner)
                for handler in getattr(stmt, "handlers", []) or []:
                    walk(handler.body)
                if is_loop:
                    # loop-carried hazard: donated in the body, never rebound
                    # before the next iteration reads it again
                    for name, (to, at) in list(dead.items()):
                        if name in before and dead[name] == before.get(name):
                            continue  # was already dead before the loop
                        body_src = stmt.body
                        rebound = any(name in binds_in(s)
                                      for s in _flat_stmts(body_src))
                        read_again = any(
                            name == d for s in _flat_stmts(body_src)
                            for d in _read_names(s))
                        if not rebound and read_again:
                            findings.append(Finding(
                                RULE, module.relpath, at,
                                f"{name!r} is donated to {to}() inside this "
                                "loop but never rebound before the next "
                                "iteration reads it again — iteration 2 feeds "
                                "a dead buffer back into the jit.",
                                fn.name,
                                module.lines[at - 1] if at <= len(module.lines) else ""))
                            del dead[name]

        def _flat_stmts(stmts):
            for s in stmts:
                yield s
                for attr in ("body", "orelse", "finalbody"):
                    inner = getattr(s, attr, None)
                    if inner:
                        yield from _flat_stmts(inner)
                for handler in getattr(s, "handlers", []) or []:
                    yield from _flat_stmts(handler.body)

        def _read_names(stmt):
            for node in ast.walk(stmt):
                d = dotted(node)
                if d is not None:
                    yield d

        walk(fn.body)
