"""graftlint checker registry — one module per rule.

A checker is any object with a ``rule`` string and a
``run(project) -> list[Finding]`` method; ``all_checkers()`` is the
single place the CLI and tests enumerate them.
"""

from __future__ import annotations

from tools.graftlint.checkers.hostsync import HostSyncChecker
from tools.graftlint.checkers.donation import DonationChecker
from tools.graftlint.checkers.asyncblock import AsyncBlockChecker
from tools.graftlint.checkers.jitpurity import JitPurityChecker
from tools.graftlint.checkers.metricsdrift import MetricsDriftChecker
from tools.graftlint.checkers.compatdrift import CompatDriftChecker


def all_checkers():
    return [
        HostSyncChecker(),
        DonationChecker(),
        AsyncBlockChecker(),
        JitPurityChecker(),
        MetricsDriftChecker(),
        CompatDriftChecker(),
    ]
