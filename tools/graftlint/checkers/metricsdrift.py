"""metrics-drift: metric names must round-trip through the registry.

``metrics/registry.py`` is the single source of truth for every
Prometheus series this system emits: dashboards
(observability/dashboards.py), alerts, and the benchmark reports all
join on those literal names. Drift is silent — a renamed series keeps
serving requests while every panel that referenced the old name reads
empty, which in production looks exactly like an outage that isn't
happening.

Three conditions, all anchored on the scanned tree's
``metrics/registry.py`` (absent registry => the checker is inert):

1. a ``Counter/Gauge/Histogram/Summary`` constructed OUTSIDE the
   registry module — metric declarations must live in one place;
2. a metric-name string literal (``seldon_*`` with a series-ish suffix)
   anywhere in the tree that no registry declaration matches — a
   dashboard/alert referencing a series that will never exist;
3. a registry declaration whose bound attribute is never read anywhere
   else in the tree — a series that exists but nothing ever records
   ("declared and vice versa" from the rule card: record => declared,
   declared => recorded).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.core import Finding, Module, Project, dotted, make_finding

RULE = "metrics-drift"

CONSTRUCTORS = {"Counter", "Gauge", "Histogram", "Summary",
                "prometheus_client.Counter", "prometheus_client.Gauge",
                "prometheus_client.Histogram", "prometheus_client.Summary"}

REGISTRY_SUFFIX = "metrics/registry.py"

# what counts as "a metric name literal" when scanning for references:
# the seldon_ prefix plus a unit/series suffix — tight enough to skip
# label names (seldon_deployment_id) and contextvars (seldon_deadline)
METRIC_NAME_RE = re.compile(
    r"^seldon_[a-z0-9_]+_(total|seconds|bytes|state|occupancy|per_step"
    r"|in_flight|inflight|steps|step|depth)$")


def _find_registry(project: Project) -> Optional[Module]:
    for m in project.modules:
        if m.relpath.replace("\\", "/").endswith(REGISTRY_SUFFIX):
            return m
    return None


def _constructor_calls(tree: ast.Module):
    """(call, name_literal_or_None, assigned_attr_or_name_or_None)."""
    out = []
    assigned_by_call: Dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            for t in node.targets:
                d = dotted(t)
                if d is not None:
                    assigned_by_call[id(node.value)] = d.rsplit(".", 1)[-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and (dotted(node.func) or "") in CONSTRUCTORS:
            name = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                name = node.args[0].value
            out.append((node, name, assigned_by_call.get(id(node))))
    return out


class MetricsDriftChecker:
    rule = RULE

    def run(self, project: Project) -> List[Finding]:
        registry = _find_registry(project)
        if registry is None:
            return []
        findings: List[Finding] = []

        declared: Set[str] = set()
        # attr/name a declaration is bound to -> (metric name, decl node)
        bindings: List[Tuple[str, str, ast.AST]] = []
        for call, name, bound in _constructor_calls(registry.tree):
            if name is not None:
                declared.add(name)
            if bound is not None:
                bindings.append((bound, name or "<dynamic>", call))

        for module in project.modules:
            is_registry = module is registry
            # 1. constructors outside the registry
            ctor_name_args = set()
            if not is_registry:
                for call, name, _ in _constructor_calls(module.tree):
                    label = f" {name!r}" if name else ""
                    if call.args:
                        ctor_name_args.add(id(call.args[0]))
                    findings.append(make_finding(
                        module, RULE, call,
                        f"Prometheus metric{label} constructed outside "
                        f"{registry.relpath} — declare it in the registry so "
                        "dashboards/alerts have one source of truth.",
                        self._enclosing(module, call)))
            # 2. metric-name literals that match nothing declared (a
            # constructor's own name arg is already covered by finding 1)
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                        and METRIC_NAME_RE.match(node.value) \
                        and node.value not in declared \
                        and id(node) not in ctor_name_args \
                        and not is_registry:
                    findings.append(make_finding(
                        module, RULE, node,
                        f"metric name {node.value!r} is referenced here but "
                        f"declared nowhere in {registry.relpath} — the series "
                        "will never exist and every panel joining on it reads "
                        "empty.", self._enclosing(module, node)))

        # 3. declared but never recorded: the bound attr/name must be READ
        #    (not just assigned) somewhere in the tree
        used: Set[str] = set()
        for module in project.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
                    used.add(node.attr)
                elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    used.add(node.id)
        for bound, name, call in bindings:
            if bound not in used:
                findings.append(make_finding(
                    registry, RULE, call,
                    f"metric {name!r} is declared (bound to {bound!r}) but "
                    "that binding is never read anywhere in the tree — a "
                    "series that exists and flatlines forever. Record it or delete "
                    "the declaration.", "MetricsRegistry"))
        return findings

    @staticmethod
    def _enclosing(module: Module, node: ast.AST) -> str:
        line = getattr(node, "lineno", 0)
        best = ""
        for n in ast.walk(module.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.lineno <= line <= (n.end_lineno or n.lineno):
                best = n.name
        return best
