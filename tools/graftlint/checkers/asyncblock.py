"""blocking-in-async: synchronous stalls inside ``async def``.

The resilience layer (runtime/resilience.py) budgets deadlines assuming
the event loop keeps turning: a breaker can only trip, a deadline can
only fire, and an admission queue can only shed if the loop is alive to
observe time passing. One ``time.sleep`` or sync ``requests`` call inside
a coroutine freezes EVERY in-flight request on that loop for its full
duration — deadlines are then enforced late or not at all.

Flags, lexically inside an ``async def`` (a sync ``def`` nested within is
a separate execution context and is skipped):

* ``time.sleep(...)``            -> ``await asyncio.sleep(...)``
* ``requests.*(...)``            -> aiohttp, or ``asyncio.to_thread``
* ``socket.*(...)`` constructors/resolvers (socket, create_connection,
  getaddrinfo, gethostbyname)    -> loop.getaddrinfo / open_connection
* ``subprocess.*(...)``          -> ``asyncio.create_subprocess_exec``
* ``.result()`` / ``.join()`` on concurrent futures or threads is NOT
  flagged (receiver types are unknowable statically); the four module
  roots above are the unambiguous offenders.
"""

from __future__ import annotations

import ast
from typing import List

from tools.graftlint.core import Finding, Module, Project, dotted, make_finding

RULE = "blocking-in-async"

SOCKET_BLOCKING = {"socket", "create_connection", "getaddrinfo",
                   "gethostbyname", "gethostbyaddr", "getfqdn"}

FIXES = {
    "time.sleep": "await asyncio.sleep(...)",
    "requests": "aiohttp (or asyncio.to_thread for a one-off)",
    "socket": "loop.getaddrinfo / asyncio.open_connection",
    "subprocess": "asyncio.create_subprocess_exec/_shell",
}


def _blocking_reason(call: ast.Call):
    d = dotted(call.func)
    if d is None:
        return None
    if d == "time.sleep":
        return "time.sleep", FIXES["time.sleep"]
    root = d.split(".", 1)[0]
    if root == "requests":
        return d, FIXES["requests"]
    if root == "socket" and d.split(".")[-1] in SOCKET_BLOCKING:
        return d, FIXES["socket"]
    if root == "subprocess":
        return d, FIXES["subprocess"]
    return None


class AsyncBlockChecker:
    rule = RULE

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for module in project.modules:
            self._walk(module.tree, module, in_async=False, qualname="",
                       findings=findings)
        return findings

    def _walk(self, node, module: Module, in_async: bool, qualname: str,
              findings: List[Finding]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.AsyncFunctionDef):
                q = f"{qualname}.{child.name}" if qualname else child.name
                self._walk(child, module, True, q, findings)
            elif isinstance(child, ast.FunctionDef):
                # nested sync def: runs whenever it is CALLED, which may be
                # off-loop (asyncio.to_thread) — do not flag its body
                q = f"{qualname}.{child.name}" if qualname else child.name
                self._walk(child, module, False, q, findings)
            elif isinstance(child, ast.ClassDef):
                q = f"{qualname}.{child.name}" if qualname else child.name
                self._walk(child, module, in_async, q, findings)
            else:
                if in_async and isinstance(child, ast.Call):
                    hit = _blocking_reason(child)
                    if hit is not None:
                        what, fix = hit
                        findings.append(make_finding(
                            module, RULE, child,
                            f"{what}() blocks the event loop inside "
                            f"'async def' — every in-flight coroutine on "
                            "this loop stalls and resilience deadlines "
                            f"fire late. Use {fix}.",
                            qualname))
                self._walk(child, module, in_async, qualname, findings)
