"""hlolint core: contracts, compiled-artifact checks, baseline, runner.

A Contract names one serving-critical jitted function, a ``build()`` hook
that returns it together with example (or ShapeDtypeStruct) arguments, and
the declared expectations on its COMPILED form. The runner lowers each
contract once (``fn.lower(*args)``), compiles it, and runs the declared
checks against two texts:

- the lowered (pre-optimization) module for the dtype audit — what the
  program ASKS for, before backend-specific rewrites (CPU legalizes bf16
  dots through f32 converts; those are backend noise, a hand-written
  ``.astype(f32)`` on the cache is not);
- the backend-optimized module for alias / transfer / collective checks
  and ``cost_analysis()`` — what XLA actually DID.

Findings are fatal (exit 1) unless waived in the contract itself
(``waivers`` — a reason is mandatory, it lives next to the contract the
way graftlint suppressions live next to the code) or grandfathered in
``tools/hlolint/baseline.json`` (fingerprint + mandatory reason, same
semantics as graftlint's baseline: entries die with the contract/detail
they describe).

Everything here is stdlib + jax; jax itself is imported lazily so the
module can be imported (e.g. by the CLI's --list) without touching the
runtime.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

CHECKS = ("alias", "transfer", "dtype", "collective", "cost")

# meta findings that can be neither waived nor baselined
META_CHECKS = ("build-error", "bad-waiver")

DEFAULT_TOLERANCE = 0.25

# HLO opcodes that move data between host and device. ``-start``/``-done``
# pairs count once (at the -start).
TRANSFER_OPCODES = ("infeed", "outfeed", "send", "recv")

# custom-call targets that smuggle a host round-trip past the opcode check
# (python callbacks, host FFI). Benign compute custom-calls (TopK, LAPACK)
# do not match.
TRANSFER_TARGET_RE = re.compile(r"callback|python|infeed|outfeed|host", re.I)

COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast", "ragged-all-to-all",
)

# result type is either one shape ("f32[4,8]{1,0}") or a tuple of shapes
# ("(f32[], u32[], token[])" — send/recv/infeed are ALWAYS tuple-typed, and
# the all-reduce combiner can merge same-shape collectives into one
# tuple-shaped op); tuples contain no nested parens, so [^()]* is exact
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?\S+\s*=\s*(?:\([^()]*\)|\S+)\s+([a-z][a-z0-9-]*)\(",
    re.M)
_CUSTOM_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
_ALIAS_PARAM_RE = re.compile(r"\(\s*(\d+)\s*,\s*\{\}\s*,\s*(?:may|must)-alias\)")
_TYPE_SIG_RE = re.compile(r"\b([a-z]+[0-9]*)\[([0-9,]*)\]")

# numpy dtype name -> HLO primitive type name
_HLO_DTYPES = {
    "float64": "f64", "float32": "f32", "float16": "f16", "bfloat16": "bf16",
    "int64": "s64", "int32": "s32", "int16": "s16", "int8": "s8",
    "uint64": "u64", "uint32": "u32", "uint16": "u16", "uint8": "u8",
    "bool": "pred",
}


def hlo_type_sig(leaf) -> str:
    """'s8[1,24,2,16]'-style signature for a jax array / ShapeDtypeStruct."""
    name = _HLO_DTYPES.get(str(leaf.dtype), str(leaf.dtype))
    return f"{name}[{','.join(str(d) for d in leaf.shape)}]"


@dataclass
class Finding:
    contract: str
    check: str  # one of CHECKS or META_CHECKS
    message: str
    # stable key for fingerprints/waivers: no volatile numbers, just the
    # identity of what broke ("arg1", "all-gather", "flops", a dtype sig)
    detail: str = ""

    def fingerprint(self) -> str:
        key = f"{self.contract}|{self.check}|{self.detail}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def render(self) -> str:
        det = f" [{self.detail}]" if self.detail else ""
        return f"{self.contract}: {self.check}{det}: {self.message}"


@dataclass
class Contract:
    """Declared compiled-form expectations for one jitted hot function.

    build() -> (jitted_fn, args): args may be concrete arrays or
    ShapeDtypeStructs — only shapes/dtypes matter to the checks.
    """

    name: str
    description: str
    build: Callable[[], Tuple[Any, tuple]]
    # call-argument positions whose EVERY leaf buffer must appear in the
    # compiled input_output_alias (donate_argnums that must have fired)
    donated: Tuple[int, ...] = ()
    # match donated leaves to aliased params by dtype only: under GSPMD the
    # entry params carry PER-DEVICE shapes, so global-shape matching would
    # misreport sharded contracts (sharding splits shapes, never dtypes)
    alias_by_dtype: bool = False
    check_transfers: bool = True
    # (regex over the LOWERED module text, why it is forbidden)
    forbid_dtypes: Tuple[Tuple[str, str], ...] = ()
    # (flattened output index, expected HLO dtype name)
    out_dtypes: Tuple[Tuple[int, str], ...] = ()
    # exact count-per-kind budget ({} = no collectives allowed);
    # None skips the check entirely
    collectives: Optional[Dict[str, int]] = None
    # check flops / bytes-accessed against budgets.json under this name
    cost: bool = False
    # "check:detail" -> reason; the contract-local analogue of graftlint's
    # inline suppression — the reason is mandatory
    waivers: Dict[str, str] = field(default_factory=dict)


class Artifact:
    """One contract lowered and compiled, with the texts the checks read."""

    def __init__(self, contract: Contract):
        fn, args = contract.build()
        self.args = args
        lowered = fn.lower(*args)
        self.stablehlo = lowered.as_text()
        self.compiled = lowered.compile()
        self.hlo = self.compiled.as_text()
        self._header = self.hlo.splitlines()[0] if self.hlo else ""
        self._cost: Optional[Dict[str, float]] = None

    # -- compiled-module parsing ------------------------------------------
    def aliased_param_indices(self) -> List[int]:
        return [int(p) for p in _ALIAS_PARAM_RE.findall(self._header)]

    def _entry_layout(self) -> Tuple[str, str]:
        """(params, results) sections of entry_computation_layout, split by
        balanced-brace scan — layouts like ``{1,0}`` defeat any regex."""
        key = "entry_computation_layout={"
        i = self._header.find(key)
        if i < 0:
            return "", ""
        j = i + len(key)
        depth, k = 1, j
        while k < len(self._header) and depth:
            c = self._header[k]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
            k += 1
        section = self._header[j:k - 1]
        arrow = section.find(")->")
        if arrow < 0:
            return section, ""
        return section[:arrow + 1], section[arrow + 3:]

    def entry_param_sigs(self) -> List[str]:
        params, _ = self._entry_layout()
        return [f"{t}[{s}]" for t, s in _TYPE_SIG_RE.findall(params)]

    def entry_result_sigs(self) -> List[str]:
        _, results = self._entry_layout()
        return [f"{t}[{s}]" for t, s in _TYPE_SIG_RE.findall(results)]

    def opcode_counts(self) -> Dict[str, int]:
        return opcode_counts_from_text(self.hlo)

    def collective_counts(self) -> Dict[str, int]:
        return collective_counts_from_text(self.hlo)

    def cost(self) -> Dict[str, float]:
        if self._cost is None:
            ca = self.compiled.cost_analysis()
            d = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
            self._cost = {
                "flops": float(d.get("flops", 0.0)),
                "bytes_accessed": float(d.get("bytes accessed", 0.0)),
            }
        return self._cost


def opcode_counts_from_text(hlo: str) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for op in _INSTR_RE.findall(hlo):
        counts[op] = counts.get(op, 0) + 1
    return counts


def collective_counts_from_text(hlo: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for op, n in opcode_counts_from_text(hlo).items():
        base = op[:-6] if op.endswith("-start") else op
        if op.endswith("-done"):
            continue
        if base in COLLECTIVE_KINDS:
            out[base] = out.get(base, 0) + n
    return out


# ----------------------------------------------------------------------
# checks
# ----------------------------------------------------------------------

def check_alias(contract: Contract, art: Artifact) -> List[Finding]:
    """Every leaf buffer of every donated call argument must be aliased to
    an output in the compiled module. XLA silently drops a donation whose
    buffer cannot alias any output (shape/dtype/size mismatch) — the
    program still runs, it just pays the full copy the donation was
    supposed to elide."""
    import jax

    def sig_of(s: str) -> str:
        return s.split("[", 1)[0] if contract.alias_by_dtype else s

    param_sigs = art.entry_param_sigs()
    pool: Dict[str, int] = {}
    for i in art.aliased_param_indices():
        if i < len(param_sigs):
            sig = sig_of(param_sigs[i])
            pool[sig] = pool.get(sig, 0) + 1
    findings: List[Finding] = []
    for argnum in contract.donated:
        missing: Dict[str, int] = {}
        for leaf in jax.tree.leaves(art.args[argnum]):
            sig = sig_of(hlo_type_sig(leaf))
            if pool.get(sig, 0) > 0:
                pool[sig] -= 1
            else:
                missing[sig] = missing.get(sig, 0) + 1
        if missing:
            what = ", ".join(f"{n}x {s}" for s, n in sorted(missing.items()))
            findings.append(Finding(
                contract.name, "alias",
                f"donated arg {argnum}: {what} missing from "
                "input_output_alias — XLA dropped the donation, every call "
                "pays a full copy of those buffers (the PR 2 aliasing "
                "contract; check shapes/shardings of input vs output)",
                detail=f"arg{argnum}"))
    return findings


def check_transfer(contract: Contract, art: Artifact) -> List[Finding]:
    findings: List[Finding] = []
    counts = art.opcode_counts()
    for op, n in sorted(counts.items()):
        base = op[:-6] if op.endswith("-start") else op
        if op.endswith("-done"):
            continue
        if base in TRANSFER_OPCODES:
            findings.append(Finding(
                contract.name, "transfer",
                f"{n}x {base} in the compiled module — a host transfer "
                "inside the hot function stalls the device stream every "
                "call (the HLO twin of graftlint's host-sync rule)",
                detail=base))
    for target in sorted(set(_CUSTOM_TARGET_RE.findall(art.hlo))):
        if TRANSFER_TARGET_RE.search(target):
            findings.append(Finding(
                contract.name, "transfer",
                f"host custom-call {target!r} in the compiled module — a "
                "python/host callback runs on the host once per call, "
                "serializing the decode pipeline",
                detail=target))
    return findings


def check_dtype(contract: Contract, art: Artifact) -> List[Finding]:
    findings: List[Finding] = []
    for pattern, why in contract.forbid_dtypes:
        n = len(re.findall(pattern, art.stablehlo))
        if n:
            findings.append(Finding(
                contract.name, "dtype",
                f"{n}x forbidden dtype signature {pattern!r} in the lowered "
                f"module: {why}",
                detail=pattern))
    if contract.out_dtypes:
        results = art.entry_result_sigs()
        for idx, want in contract.out_dtypes:
            got = results[idx].split("[", 1)[0] if idx < len(results) else "<absent>"
            if got != want:
                findings.append(Finding(
                    contract.name, "dtype",
                    f"output {idx} is {got}, contract requires {want} — a "
                    "widened output dtype doubles that tensor's HBM traffic "
                    "on every call",
                    detail=f"out{idx}"))
    return findings


def check_collective(contract: Contract, art: Artifact) -> List[Finding]:
    budget = contract.collectives or {}
    actual = art.collective_counts()
    findings: List[Finding] = []
    for kind in sorted(set(budget) | set(actual)):
        want, got = budget.get(kind, 0), actual.get(kind, 0)
        if got != want:
            direction = "extra" if got > want else "missing"
            findings.append(Finding(
                contract.name, "collective",
                f"{kind}: compiled module has {got}, contract budgets {want} "
                f"({direction}) — an unbudgeted collective is a reshard the "
                "declared sharding never asked for (ICI time on every step)",
                detail=kind))
    return findings


def check_cost(contract: Contract, art: Artifact, budgets: dict,
               diff_out: Dict[str, dict]) -> List[Finding]:
    actual = art.cost()
    entry = (budgets.get("entries") or {}).get(contract.name)
    tol = float((entry or {}).get(
        "tolerance", budgets.get("tolerance", DEFAULT_TOLERANCE)))
    findings: List[Finding] = []
    record: Dict[str, dict] = {}
    if entry is None:
        findings.append(Finding(
            contract.name, "cost",
            "no committed budget in budgets.json — run "
            "`python -m tools.hlolint --update-budgets`, review the "
            "snapshot, and commit it",
            detail="missing-budget"))
        record = {k: {"actual": v, "budget": None} for k, v in actual.items()}
    else:
        for key, got in actual.items():
            want = float(entry.get(key, 0.0))
            rel = abs(got - want) / max(abs(want), 1.0)
            record[key] = {"actual": got, "budget": want, "rel_delta": rel,
                           "tolerance": tol}
            if rel > tol:
                findings.append(Finding(
                    contract.name, "cost",
                    f"{key} drifted {rel:+.1%} past the ±{tol:.0%} band "
                    f"(budget {want:,.0f}, compiled {got:,.0f}) — the PR 2/3 "
                    "bandwidth wins are CI invariants; if the change is "
                    "intentional, re-baseline with --update-budgets and say "
                    "why in the commit",
                    detail=key))
    diff_out[contract.name] = record
    return findings


# ----------------------------------------------------------------------
# budgets + baseline
# ----------------------------------------------------------------------

def load_budgets(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def save_budgets(path: str, measured: Dict[str, Dict[str, float]],
                 previous: Optional[dict] = None) -> None:
    previous = previous or {}
    entries = dict(previous.get("entries") or {})
    for name, cost in measured.items():
        old = dict(entries.get(name) or {})
        old.update({k: round(v, 1) for k, v in cost.items()})
        entries[name] = old
    payload = {
        "_comment": "hlolint compiled-cost budgets (flops / bytes accessed "
                    "per contract, from HLO cost analysis under "
                    "JAX_PLATFORMS=cpu + the virtual 8-device mesh). "
                    "Re-baseline ONLY for intentional changes: "
                    "python -m tools.hlolint --update-budgets, then review "
                    "the diff — see docs/static-analysis.md.",
        "tolerance": previous.get("tolerance", DEFAULT_TOLERANCE),
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry; ValueError on reason-less entries. The file
    format and validation ARE graftlint's (one validator, one auditability
    bar) — only the fingerprint contents differ (contract|check|detail
    instead of rule|path|function|line)."""
    from tools.graftlint.core import load_baseline as _graftlint_load

    return _graftlint_load(path)


def apply_baseline(findings: Sequence[Finding], baseline: Dict[str, dict]):
    budget = {fp: e.get("count", 1) for fp, e in baseline.items()}
    reported: List[Finding] = []
    absorbed: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if f.check in CHECKS and budget.get(fp, 0) > 0:
            budget[fp] -= 1
            absorbed.append(f)
        else:
            reported.append(f)
    return reported, absorbed


# ----------------------------------------------------------------------
# runner
# ----------------------------------------------------------------------

def run_contracts(
    contracts: Sequence[Contract],
    budgets: Optional[dict] = None,
    baseline: Optional[Dict[str, dict]] = None,
    checks: Optional[Sequence[str]] = None,
    jobs: int = 1,
):
    """Lower+compile each contract and run its declared checks.

    Returns (reported, absorbed, waived, budget_diff, measured_costs).
    ``reported`` non-empty => the gate fails. ``measured_costs`` holds the
    compiled cost of every cost-checked contract (for --update-budgets).

    ``jobs > 1`` builds the Artifacts (the expensive lower+compile step)
    in a thread pool — XLA compilation releases the GIL, and the lazy
    contract fixtures are lock-guarded (tools/hlolint/contracts.py) —
    then runs the checks serially in declaration order, so findings and
    budget diffs are byte-identical to the serial run.
    """
    active = set(checks or CHECKS)
    unknown = active - set(CHECKS)
    if unknown:
        raise ValueError(f"unknown check(s): {', '.join(sorted(unknown))}")
    budgets = budgets or {}
    baseline = baseline or {}
    findings: List[Finding] = []
    waived: List[Finding] = []
    budget_diff: Dict[str, dict] = {}
    measured: Dict[str, Dict[str, float]] = {}

    prebuilt: Dict[str, object] = {}
    if jobs > 1 and len(contracts) > 1:
        from concurrent.futures import ThreadPoolExecutor

        def build(c):
            try:
                return Artifact(c)
            except Exception as e:  # noqa: BLE001 — reported per contract below
                return e

        # The first contract is built alone to warm the shared lazy
        # fixtures (base server, TP server, batcher): building it inside
        # the pool would just park every worker on the fixture lock
        # behind one loader, paying thread overhead for no overlap.
        prebuilt[contracts[0].name] = build(contracts[0])
        rest = contracts[1:]
        with ThreadPoolExecutor(max_workers=min(int(jobs), len(rest))) as pool:
            for c, art in zip(rest, pool.map(build, rest)):
                prebuilt[c.name] = art

    for contract in contracts:
        for key, reason in contract.waivers.items():
            if not str(reason).strip():
                findings.append(Finding(
                    contract.name, "bad-waiver",
                    f"waiver {key!r} has no reason — the reason is "
                    "mandatory, it is the audit trail",
                    detail=key))
        try:
            art = prebuilt.get(contract.name)
            if art is None:
                art = Artifact(contract)
            elif isinstance(art, Exception):
                raise art
        except Exception as e:  # noqa: BLE001 — any build/lower/compile failure is the finding
            findings.append(Finding(
                contract.name, "build-error",
                f"contract failed to build/lower/compile: "
                f"{type(e).__name__}: {e}",
                detail="build"))
            continue
        local: List[Finding] = []
        if "alias" in active and contract.donated:
            local.extend(check_alias(contract, art))
        if "transfer" in active and contract.check_transfers:
            local.extend(check_transfer(contract, art))
        if "dtype" in active and (contract.forbid_dtypes or contract.out_dtypes):
            local.extend(check_dtype(contract, art))
        if "collective" in active and contract.collectives is not None:
            local.extend(check_collective(contract, art))
        if "cost" in active and contract.cost:
            local.extend(check_cost(contract, art, budgets, budget_diff))
            measured[contract.name] = art.cost()
        for f in local:
            reason = contract.waivers.get(f"{f.check}:{f.detail}", "").strip()
            if reason:
                waived.append(f)
            else:
                findings.append(f)

    reported, absorbed = apply_baseline(findings, baseline)
    reported.sort(key=lambda f: (f.contract, f.check, f.detail))
    return reported, absorbed, waived, budget_diff, measured
