"""hlolint — compiled-artifact contract checking for the serving hot paths.

graftlint (tools/graftlint) guards the SOURCE: host syncs, use-after-donate,
impure jit bodies. The perf invariants of the decode path, though, live in
what XLA actually compiles — a donation silently degrades to a copy when
buffer shapes mismatch, a dtype upcast sneaks f32 into the int8 KV read, a
stray reshard adds all-gathers to the TP decode step. None of that is
visible to an AST walk. hlolint lowers the serving-critical jitted
functions to StableHLO / optimized HLO and enforces a declared contract
per function (docs/static-analysis.md):

- ``alias``      every donated argument's buffers appear in the compiled
                 module's ``input_output_alias`` (donation actually fired);
- ``transfer``   zero host transfers (infeed/outfeed/send/recv, host
                 callbacks) inside the compiled hot function;
- ``dtype``      forbidden dtype/shape signatures never appear in the
                 lowered module (the int8 KV path never materializes f32
                 KV tensors), and declared output dtypes hold;
- ``collective`` the compiled collective set matches the declared
                 count-per-kind budget exactly — anything extra fails;
- ``cost``       HLO cost analysis (flops / bytes accessed) stays inside
                 a tolerance band around the committed budgets.json.

``python -m tools.hlolint seldon_core_tpu/`` exits 0 = every contract
holds. Same enforcement posture as graftlint: findings are fatal unless
waived in the contract registry (with a reason, next to the contract) or
grandfathered in tools/hlolint/baseline.json (with a reason).
"""

from tools.hlolint.core import (  # noqa: F401
    CHECKS,
    Contract,
    Finding,
    load_baseline,
    load_budgets,
    run_contracts,
    save_budgets,
)
