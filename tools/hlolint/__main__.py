"""hlolint CLI.

    python -m tools.hlolint [paths...]
        [--contracts name1,name2] [--checks alias,cost,...]
        [--budgets FILE] [--update-budgets]
        [--baseline FILE | --no-baseline]
        [--budget-diff FILE] [--format text|json] [--list] [--verbose]

Exit codes: 0 every contract holds, 1 findings, 2 usage/configuration
error. The positional paths are a sanity anchor (the tree the contracts
compile from must exist); contract selection is by --contracts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    # contract construction is jax-free (builders are lazy), so --list and
    # usage errors stay instant; the platform pin below runs only before
    # the first real lowering
    from tools.hlolint.contracts import all_contracts, ensure_platform
    from tools.hlolint.core import (
        CHECKS, load_baseline, load_budgets, run_contracts, save_budgets)

    here = os.path.dirname(__file__)
    default_budgets = os.path.join(here, "budgets.json")
    default_baseline = os.path.join(here, "baseline.json")

    parser = argparse.ArgumentParser(
        prog="python -m tools.hlolint",
        description="compiled-artifact contract checking "
                    "(docs/static-analysis.md)")
    parser.add_argument("paths", nargs="*", default=["seldon_core_tpu"],
                        help="tree the contracts compile from "
                             "(default: seldon_core_tpu)")
    parser.add_argument("--contracts", default=None,
                        help="comma-separated subset of contract names")
    parser.add_argument("--checks", default=None,
                        help="comma-separated subset of: " + ", ".join(CHECKS))
    parser.add_argument("--budgets", default=None,
                        help=f"cost budgets JSON (default: {default_budgets})")
    parser.add_argument("--update-budgets", action="store_true",
                        help="write the measured compiled costs to the "
                             "budgets file (review the diff before committing)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline JSON (default: {default_baseline} "
                             "when it exists)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline: report every finding")
    parser.add_argument("--budget-diff", default=None,
                        help="write the budget-vs-compiled cost diff as JSON "
                             "(CI uploads this as an artifact on failure)")
    parser.add_argument("--jobs", type=int, default=1, metavar="N",
                        help="lower+compile contract artifacts in N threads "
                             "(checks still run serially in declaration "
                             "order, so output is identical to --jobs 1)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--list", action="store_true",
                        help="list contract names and exit")
    parser.add_argument("--verbose", action="store_true",
                        help="also list waived/baselined findings")
    args = parser.parse_args(argv)

    contracts = all_contracts()
    if args.list:
        for c in contracts:
            print(f"{c.name}: {c.description}")
        return 0

    for p in (args.paths or ["seldon_core_tpu"]):
        if not os.path.exists(p):
            print(f"hlolint: path does not exist: {p}", file=sys.stderr)
            return 2

    if args.contracts:
        wanted = [c.strip() for c in args.contracts.split(",") if c.strip()]
        by_name = {c.name: c for c in contracts}
        unknown = [w for w in wanted if w not in by_name]
        if unknown:
            print(f"hlolint: unknown contract(s): {', '.join(unknown)} "
                  f"(have: {', '.join(sorted(by_name))})", file=sys.stderr)
            return 2
        contracts = [by_name[w] for w in wanted]

    checks = None
    if args.checks:
        checks = [c.strip() for c in args.checks.split(",") if c.strip()]
        unknown_checks = set(checks) - set(CHECKS)
        if unknown_checks:
            print(f"hlolint: unknown check(s): "
                  f"{', '.join(sorted(unknown_checks))}", file=sys.stderr)
            return 2

    budgets_path = args.budgets or default_budgets
    budgets = {}
    if os.path.exists(budgets_path):
        budgets = load_budgets(budgets_path)
    elif args.budgets and not args.update_budgets:
        print(f"hlolint: budgets file not found: {args.budgets}",
              file=sys.stderr)
        return 2

    baseline = {}
    if not args.no_baseline:
        baseline_path = args.baseline or (
            default_baseline if os.path.exists(default_baseline) else None)
        if args.baseline and not os.path.exists(args.baseline):
            print(f"hlolint: baseline not found: {args.baseline}",
                  file=sys.stderr)
            return 2
        if baseline_path:
            try:
                baseline = load_baseline(baseline_path)
            except ValueError as e:
                print(f"hlolint: {e}", file=sys.stderr)
                return 2

    # Pin the lowering environment BEFORE jax (imported transitively by the
    # contract builders) initializes its backend: the budgets are snapshots
    # of the CPU + virtual-8-mesh environment, the same one CI tests use.
    ensure_platform()
    try:
        reported, absorbed, waived, budget_diff, measured = run_contracts(
            contracts, budgets=budgets, baseline=baseline, checks=checks,
            jobs=args.jobs)
    except ValueError as e:
        print(f"hlolint: {e}", file=sys.stderr)
        return 2

    if args.update_budgets:
        save_budgets(budgets_path, measured, previous=budgets)
        print(f"hlolint: wrote {len(measured)} cost budget(s) to "
              f"{budgets_path} — review the diff before committing")
        # still report the non-cost findings so --update-budgets cannot
        # green-wash a broken alias/transfer/dtype/collective contract
        reported = [f for f in reported if f.check != "cost"]

    if args.budget_diff:
        with open(args.budget_diff, "w", encoding="utf-8") as f:
            json.dump(budget_diff, f, indent=2)

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in reported],
            "baselined": len(absorbed),
            "waived": len(waived),
            "budget_diff": budget_diff,
        }, indent=2))
    else:
        for f in reported:
            print(f.render())
        if args.verbose:
            for f in waived:
                print(f"[waived]    {f.render()}")
            for f in absorbed:
                print(f"[baselined] {f.render()}")
        print(f"hlolint: {len(reported)} finding(s) over {len(contracts)} "
              f"contract(s) ({len(waived)} waived, {len(absorbed)} baselined)",
              file=sys.stderr)
    return 1 if reported else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `--list | head` is normal usage, not an error
        sys.exit(0)
