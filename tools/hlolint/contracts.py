"""The hlolint contract registry: the serving-critical jitted functions and
their declared compiled-form contracts.

Contracts compile a PRODUCTION-SHAPED configuration at test dims: the
bf16-compute transformer with the int8 KV cache (the PR 2 serving layout)
at llama-tiny sizes, on the CPU backend with the virtual 8-device mesh —
the same lowering environment as CI's unit tests. Budgets in budgets.json
are snapshots of THIS environment; the contracts are about structure
(aliases, transfers, dtypes, collective sets) and relative cost, which is
what survives the CPU-for-TPU substitution.

Shared fixtures are lazy singletons: one base server feeds the prefill /
extend / decode / decode-step / batcher contracts so the registry costs a
handful of tiny compiles, not a model load per contract.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List

from tools.hlolint.core import Contract

# tiny-but-production-shaped dims, shared by every LLM contract
PLEN = 16          # prompt bucket
MAX_LEN = 24       # cache length (prompt bucket + decode headroom)
SLOTS = 4          # continuous-batcher slots
N_STEPS = 7        # decode scan length (max_new_tokens - 1)
KV_HEADS = 2       # llama-tiny n_kv_heads
HEAD_DIM = 16      # llama-tiny head_dim
# paged layout (PR 7): 8-token pages, 3 pages/slot view, and an
# OVERSUBSCRIBED pool (10 pages = 8 usable + 2 reserved, vs the 12 a fully
# provisioned 4-slot pool would need) — the contract compiles the pool
# shape serving actually runs, so the cost budget records the paged step's
# bytes against a pool smaller than the dense slot cache
PAGE_SIZE = 8
PAGES_PER_SLOT = 3  # ceil(MAX_LEN / PAGE_SIZE)
POOL_PAGES = 10
# speculative decoding (PR 8): draft depth of the verify-step contracts —
# the serving default, so the cost budget records the K+1=5-token-wide
# verify forward serving actually dispatches
SPEC_K = 4
# reserved rows leading every staged page bucket (models/transformer.py
# RESERVED_PAGES — named locally so the contract dims read in one place)
RESERVED_PAGES_N = 2
# batched LoRA (ISSUE 15): the adapted-step contracts compile a small
# dense adapter pool — rank 2 x 4 rows (identity + 3 tenants). At these
# toy dims the adapter machinery is a far larger FRACTION of the step
# than at 7B (the 64-wide projections are nearly free while the
# gather+einsum overhead is fixed), so the rank is chosen to keep the
# adapted step INSIDE the plain step's tolerance band — the
# near-base-model-throughput claim tests/test_adapters.py pins against
# budgets.json; at serving dims the margin only widens.
LORA_RANK = 2
LORA_ADAPTERS = 4


def ensure_platform() -> None:
    """Pin the lowering environment BEFORE jax initializes: CPU backend
    with 8 virtual devices (the CI mesh). Mirrors tests/conftest.py — the
    axon TPU plugin ignores JAX_PLATFORMS, so the config update after
    import is required too."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")


_STATE: Dict[str, object] = {}
# --jobs builds contract artifacts in a thread pool; the lazy fixtures
# below are check-then-act on _STATE, so unlocked concurrent builders
# would each load (and compile) their own server. RLock because fixtures
# nest (_batcher builds on _base_server).
_STATE_LOCK = threading.RLock()


def _base_server():
    """bf16 compute + int8 KV llama-tiny LLMServer — the serving layout the
    PR 2/3 perf work targets, at test dims."""
    with _STATE_LOCK:
        if "server" not in _STATE:
            ensure_platform()
            from seldon_core_tpu.servers.llmserver import LLMServer

            s = LLMServer(
                model="llama-tiny", model_kwargs={"dtype": "bfloat16"},
                init_random=True, max_new_tokens=N_STEPS + 1,
                len_buckets=(PLEN,), batch_buckets=(1, SLOTS), seed=7,
                kv_cache_dtype="int8",
            )
            s.load()
            _STATE["server"] = s
        return _STATE["server"]


def _tp_server():
    """tensor_parallel=2 over the virtual 8-mesh: the TP decode contract."""
    with _STATE_LOCK:
        if "tp_server" not in _STATE:
            ensure_platform()
            from seldon_core_tpu.servers.llmserver import LLMServer

            s = LLMServer(
                model="llama-tiny", model_kwargs={"dtype": "bfloat16"},
                init_random=True, max_new_tokens=N_STEPS + 1,
                len_buckets=(PLEN,), batch_buckets=(1,), seed=7,
                kv_cache_dtype="int8", tensor_parallel=2,
            )
            s.load()
            _STATE["tp_server"] = s
        return _STATE["tp_server"]


def _draft_server():
    """base-server layout plus a draft model (spec_mode='draft'): the
    draft shares the target's config — what matters to the contract is
    the compiled SHAPE of the fused draft+verify program, not drafting
    quality."""
    with _STATE_LOCK:
        if "draft_server" not in _STATE:
            ensure_platform()
            from seldon_core_tpu.servers.llmserver import LLMServer

            s = LLMServer(
                model="llama-tiny", model_kwargs={"dtype": "bfloat16"},
                init_random=True, max_new_tokens=N_STEPS + 1,
                len_buckets=(PLEN,), batch_buckets=(1, SLOTS), seed=7,
                kv_cache_dtype="int8", spec_mode="draft",
                draft_model="llama-tiny",
                draft_model_kwargs={"dtype": "bfloat16"},
            )
            s.load()
            _STATE["draft_server"] = s
        return _STATE["draft_server"]


def _lora_server():
    """base-server layout plus the batched-LoRA adapter pool
    (rank LORA_RANK=2, LORA_ADAPTERS=4 rows — see the constants' comment
    for why rank 2): the adapted decode/verify-step contracts. The
    pool rides into the compiled step as an un-donated pytree argument
    plus per-slot adapter ids — the registry swaps pools functionally on
    load/evict, so the program must never alias them."""
    with _STATE_LOCK:
        if "lora_server" not in _STATE:
            ensure_platform()
            from seldon_core_tpu.servers.llmserver import LLMServer

            s = LLMServer(
                model="llama-tiny", model_kwargs={"dtype": "bfloat16"},
                init_random=True, max_new_tokens=N_STEPS + 1,
                len_buckets=(PLEN,), batch_buckets=(1, SLOTS), seed=7,
                kv_cache_dtype="int8", lora_rank=LORA_RANK,
                lora_max_adapters=LORA_ADAPTERS,
            )
            s.load()
            _STATE["lora_server"] = s
        return _STATE["lora_server"]


def _batcher():
    with _STATE_LOCK:  # nests into _base_server's hold: RLock
        if "batcher" not in _STATE:
            from seldon_core_tpu.runtime.batcher import ContinuousBatcher

            # layout pinned: these contracts cover the DENSE slot pool
            # (insert/set_slot); the paged pool has its own contracts below
            _STATE["batcher"] = ContinuousBatcher(
                _base_server(), max_slots=SLOTS, max_len=MAX_LEN,
                layout="dense")
        return _STATE["batcher"]


def _paged_batcher():
    with _STATE_LOCK:  # nests into _base_server's hold: RLock
        if "paged_batcher" not in _STATE:
            from seldon_core_tpu.runtime.batcher import ContinuousBatcher

            _STATE["paged_batcher"] = ContinuousBatcher(
                _base_server(), max_slots=SLOTS, max_len=MAX_LEN,
                layout="paged", page_size=PAGE_SIZE, pool_pages=POOL_PAGES,
                prefill_chunk=PAGE_SIZE)
        return _STATE["paged_batcher"]


def _cache_specs(batch: int):
    """ShapeDtypeStruct pytree of the int8 KV caches — the checks only
    need shapes/dtypes, so nothing is materialized."""
    import jax

    from seldon_core_tpu.models.transformer import init_kv_caches

    s = _base_server()
    return jax.eval_shape(
        lambda: init_kv_caches(s._cfg, batch, MAX_LEN, s.kv_cache_dtype))


def _paged_cache_specs():
    """ShapeDtypeStruct pytree of the int8 paged pool (10 pages x 8
    tokens) — shapes/dtypes only, nothing materialized."""
    import jax

    from seldon_core_tpu.models.transformer import init_paged_kv_caches

    s = _base_server()
    return jax.eval_shape(
        lambda: init_paged_kv_caches(
            s._cfg, POOL_PAGES, PAGE_SIZE, s.kv_cache_dtype))


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.dtype(dtype))


# full-KV-cache dtype signatures in the LOWERED module: an f32 tensor of
# the whole cache shape means the int8 path materialized a dequantized
# (or upcast) copy of the cache — the exact regression the int8 KV work
# exists to prevent. bf16 full-cache tensors are the expected dequant
# target and are allowed.
def _f32_cache_sig(batch: int) -> str:
    return rf"tensor<{batch}x{MAX_LEN}x{KV_HEADS}x{HEAD_DIM}xf32>"


F32_CACHE_WHY = (
    "a full-cache f32 tensor in the int8 KV path means the quantized "
    "cache was dequantized/upcast wholesale (2-4x the HBM traffic the "
    "int8 layout bought back)"
)


# same regression class for the paged pool: a whole-pool f32 tensor means
# the int8 pages were dequantized/upcast wholesale
def _f32_pool_sig() -> str:
    return rf"tensor<{POOL_PAGES}x{PAGE_SIZE}x{KV_HEADS}x{HEAD_DIM}xf32>"


# ----------------------------------------------------------------------
# builders
# ----------------------------------------------------------------------

def _build_prefill():
    s = _base_server()
    fn = s._get_prefill(1, PLEN, MAX_LEN)
    return fn, (s._params, _sds((1, PLEN), "int32"), _sds((1, PLEN), "int32"))


def _build_extend():
    s = _base_server()
    fn = s._get_extend(1, PLEN, MAX_LEN, donate=True)
    return fn, (s._params, _cache_specs(1), _sds((1, PLEN), "int32"),
                _sds((1, PLEN), "int32"), _sds((), "int32"))


def _build_decode_scan():
    s = _base_server()
    fn = s._get_decode(1, MAX_LEN, donate=True)
    return fn, (s._params, _cache_specs(1), _sds((1,), "int32"),
                _sds((1,), "int32"), N_STEPS, _sds((2,), "uint32"),
                _sds((), "float32"))


def _build_decode_step():
    s = _base_server()
    fn = s._get_decode_step(SLOTS, MAX_LEN, 1)
    return fn, (s._params, _cache_specs(SLOTS), _sds((SLOTS,), "int32"),
                _sds((SLOTS,), "int32"), _sds((SLOTS, 2), "uint32"),
                _sds((), "float32"))


def _build_decode_scan_tp2():
    import jax

    s = _tp_server()
    fn = s._get_decode(1, MAX_LEN, donate=True)
    from seldon_core_tpu.models.transformer import init_kv_caches

    caches = jax.eval_shape(
        lambda: init_kv_caches(s._cfg, 1, MAX_LEN, s.kv_cache_dtype))
    return fn, (s._params, caches, _sds((1,), "int32"), _sds((1,), "int32"),
                N_STEPS, _sds((2,), "uint32"), _sds((), "float32"))


def _build_batcher_insert():
    b = _batcher()
    import jax

    from seldon_core_tpu.models.transformer import init_kv_caches

    s = _base_server()
    small = jax.eval_shape(
        lambda: init_kv_caches(s._cfg, 1, MAX_LEN, s.kv_cache_dtype))
    return b._insert, (b._caches, small, _sds((), "int32"))


def _build_batcher_set_slot():
    b = _batcher()
    return b._set_slot, (b._last_tok, b._next_pos, b._keys,
                         _sds((), "int32"), _sds((), "int32"),
                         _sds((), "int32"), _sds((2,), "uint32"))


def _build_paged_decode_step():
    s = _base_server()
    fn = s._get_decode_step_paged(SLOTS, PAGES_PER_SLOT, 1)
    return fn, (s._params, _paged_cache_specs(), _sds((SLOTS,), "int32"),
                _sds((SLOTS,), "int32"), _sds((SLOTS, 2), "uint32"),
                _sds((), "float32"),
                _sds((SLOTS, PAGES_PER_SLOT), "int32"))


def _build_prefill_chunk():
    s = _base_server()
    fn = s._get_prefill_chunk(PAGE_SIZE, PAGES_PER_SLOT)
    return fn, (s._params, _paged_cache_specs(),
                _sds((1, PAGES_PER_SLOT), "int32"),
                _sds((1, PAGE_SIZE), "int32"), _sds((1, PAGE_SIZE), "int32"))


def _build_set_block_row():
    b = _paged_batcher()
    return b._set_block_row, (b._block_tables, _sds((), "int32"),
                              _sds((PAGES_PER_SLOT,), "int32"))


def _build_reset_pages():
    b = _paged_batcher()
    return b._reset_pages, (_paged_cache_specs(),
                            _sds((PAGES_PER_SLOT,), "int32"))


def _build_handoff_import():
    """Disaggregated KV handoff, decode-side import (PR 9): the staged
    pool a prefill worker moved device-to-device
    (runtime/disagg.py ``PrefillWorker``) scattered whole-pages into the
    slot pool through the admission's block row
    (runtime/batcher.py ``_get_handoff_import``). The staged pool has the
    worker's single-sequence shape: RESERVED_PAGES + pages-per-slot."""
    import jax

    from seldon_core_tpu.models.transformer import (RESERVED_PAGES,
                                                    init_paged_kv_caches)

    b = _paged_batcher()
    fn = b._get_handoff_import()
    s = _base_server()
    staged = jax.eval_shape(
        lambda: init_paged_kv_caches(
            s._cfg, RESERVED_PAGES + PAGES_PER_SLOT, PAGE_SIZE,
            s.kv_cache_dtype))
    return fn, (_paged_cache_specs(), staged,
                _sds((PAGES_PER_SLOT,), "int32"), _sds((), "int32"))


def _build_verify_step_k4():
    """ngram spec step over the PAGED pool: the serving-default
    speculative hot function (self-draft, zero extra weights)."""
    s = _base_server()
    fn = s._get_spec_step(SLOTS, SPEC_K, MAX_LEN, mode="ngram",
                          layout="paged", n_pages=PAGES_PER_SLOT)
    return fn, (s._params, _paged_cache_specs(), _sds((SLOTS,), "int32"),
                _sds((SLOTS,), "int32"), _sds((SLOTS, 2), "uint32"),
                _sds((), "float32"),
                _sds((SLOTS, PAGES_PER_SLOT), "int32"),
                _sds((SLOTS, MAX_LEN), "int32"), _sds((SLOTS,), "int32"))


def _build_verify_step_dense_k4():
    """ngram spec step over the DENSE slot cache (the A/B reference
    layout): same program shape, per-position scatter instead of the
    block-table redirect."""
    s = _base_server()
    fn = s._get_spec_step(SLOTS, SPEC_K, MAX_LEN, mode="ngram",
                          layout="dense")
    return fn, (s._params, _cache_specs(SLOTS), _sds((SLOTS,), "int32"),
                _sds((SLOTS,), "int32"), _sds((SLOTS, 2), "uint32"),
                _sds((), "float32"),
                _sds((SLOTS, MAX_LEN), "int32"), _sds((SLOTS,), "int32"))


def _build_draft_verify_step_k4():
    """draft-model spec step (dense layout): K+1 sequential draft
    forwards fused with the single K+1-token target verify, the draft's
    own dense cache donated through the program alongside the target's."""
    import jax

    from seldon_core_tpu.models.transformer import init_kv_caches

    s = _draft_server()
    fn = s._get_spec_step(SLOTS, SPEC_K, MAX_LEN, mode="draft",
                          layout="dense")
    dcaches = jax.eval_shape(
        lambda: init_kv_caches(s._draft_cfg, SLOTS, MAX_LEN))
    return fn, (s._params, _cache_specs(SLOTS), _sds((SLOTS,), "int32"),
                _sds((SLOTS,), "int32"), _sds((SLOTS, 2), "uint32"),
                _sds((), "float32"),
                _sds((SLOTS, MAX_LEN), "int32"), _sds((SLOTS,), "int32"),
                s._draft_params, dcaches)


def _build_lora_decode_step():
    """Batched-LoRA paged decode step (ISSUE 15): the plain pipelined
    step plus one gather+einsum pair per adapted q/o/FFN projection,
    factors gathered from the dense pool by the per-slot adapter ids.
    Serving state donates exactly like the plain step; the pool and ids
    are long-lived shared state and must NOT alias."""
    s = _lora_server()
    fn = s._get_decode_step_paged(SLOTS, PAGES_PER_SLOT, 1, lora=True)
    return fn, (s._params, _paged_cache_specs(), _sds((SLOTS,), "int32"),
                _sds((SLOTS,), "int32"), _sds((SLOTS, 2), "uint32"),
                _sds((), "float32"),
                _sds((SLOTS, PAGES_PER_SLOT), "int32"),
                s.adapter_registry.pool(), _sds((SLOTS,), "int32"))


def _build_lora_verify_step():
    """Batched-LoRA speculative verify step (ISSUE 15): the ngram
    draft+verify program with the per-slot adapter deltas applied in the
    TARGET forward (drafting stays base-model — the chain-exact accept
    loop enforces the adapted distribution either way)."""
    s = _lora_server()
    fn = s._get_spec_step(SLOTS, SPEC_K, MAX_LEN, mode="ngram",
                          layout="paged", n_pages=PAGES_PER_SLOT, lora=True)
    return fn, (s._params, _paged_cache_specs(), _sds((SLOTS,), "int32"),
                _sds((SLOTS,), "int32"), _sds((SLOTS, 2), "uint32"),
                _sds((), "float32"),
                _sds((SLOTS, PAGES_PER_SLOT), "int32"),
                _sds((SLOTS, MAX_LEN), "int32"), _sds((SLOTS,), "int32"),
                s.adapter_registry.pool(), _sds((SLOTS,), "int32"))


def _build_set_hist_row():
    b = _batcher()
    return b._set_hist_row, (_sds((SLOTS, MAX_LEN), "int32"),
                             _sds((), "int32"), _sds((MAX_LEN,), "int32"))


def _build_cow_page_copy():
    """Radix prefix cache, copy-on-write page copy (PR 12): ONE page's
    values move src -> dst across layers, the position row masked to the
    valid token count — the only copy a prefix hit can cost (full shared
    blocks are block-table entries)."""
    b = _paged_batcher()
    return b._cow_page_copy, (_paged_cache_specs(), _sds((), "int32"),
                              _sds((), "int32"), _sds((), "int32"))


def _build_prefix_export():
    """Radix prefix cache, disaggregated prefix export (PR 12): gather the
    decode pool's cached-prefix pages into a handoff-shaped bucket (2
    reserved rows + a power-of-two page bucket) for the D2D ship to a
    prefill worker — the pool is NOT donated (the trie's pages stay
    live), and the bytes are the bucket's, never the pool's."""
    b = _paged_batcher()
    return b._export_pages, (_paged_cache_specs(),
                             _sds((RESERVED_PAGES_N + 2,), "int32"))


def _build_jaxserver_predict():
    ensure_platform()
    import jax.numpy as jnp

    with _STATE_LOCK:
        if "jaxserver" not in _STATE:
            import jax

            from seldon_core_tpu.models import get_model
            from seldon_core_tpu.servers.jaxserver import JAXServer, export_checkpoint

            m = get_model("mlp", features=(16,), num_classes=4)
            params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
            # held in _STATE so the checkpoint dir is removed at interpreter
            # exit instead of leaking one temp dir per hlolint run
            tmp = tempfile.TemporaryDirectory(prefix="hlolint-jaxserver-")
            _STATE["jaxserver_tmp"] = tmp
            export_checkpoint(tmp.name, "mlp", params,
                              kwargs={"features": (16,), "num_classes": 4},
                              input_shape=[8], use_orbax=False)
            js = JAXServer(model_uri=tmp.name, batch_buckets=(4,))
            js.load()
            _STATE["jaxserver"] = js
        js = _STATE["jaxserver"]
    return js._apply, (js._params, _sds((4, 8), "float32"))


def _build_fused_norm():
    ensure_platform()
    import jax

    from seldon_core_tpu.ops.fused_norm import fused_residual_rmsnorm

    fn = jax.jit(lambda x, h, w: fused_residual_rmsnorm(x, h, w, 1e-5))
    return fn, (_sds((8, 2048), "bfloat16"), _sds((8, 2048), "bfloat16"),
                _sds((2048,), "float32"))


def _build_ring_attention():
    ensure_platform()
    import jax

    from seldon_core_tpu.ops.ring_attention import ring_attention
    from seldon_core_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"seq": 8})
    fn = jax.jit(lambda q, k, v, p: ring_attention(q, k, v, p, p, mesh=mesh))
    qkv = _sds((1, 64, 4, HEAD_DIM), "bfloat16")
    return fn, (qkv, qkv, qkv, _sds((1, 64), "int32"))


# ----------------------------------------------------------------------
# the registry
# ----------------------------------------------------------------------

def all_contracts() -> List[Contract]:
    return [
        Contract(
            name="llm.prefill_b1",
            description="LLMServer prefill (b=1, plen=16) into the int8 cache",
            build=_build_prefill,
            check_transfers=True,
            forbid_dtypes=((_f32_cache_sig(1), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.extend_b1",
            description="LLMServer suffix prefill (donating variant): the "
                        "scatter must update the cache in place",
            build=_build_extend,
            donated=(1,),
            forbid_dtypes=((_f32_cache_sig(1), F32_CACHE_WHY),),
            collectives={},
        ),
        Contract(
            name="llm.decode_scan_b1",
            description="LLMServer fused decode scan (b=1): generate()'s "
                        "device-side token loop",
            build=_build_decode_scan,
            donated=(1,),
            forbid_dtypes=((_f32_cache_sig(1), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.decode_step_s4",
            description="ContinuousBatcher pipelined decode step (S=4, k=1): "
                        "THE hot function of served decode",
            build=_build_decode_step,
            donated=(1, 3, 4),
            forbid_dtypes=((_f32_cache_sig(SLOTS), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.decode_scan_tp2",
            description="decode scan under tensor_parallel=2 on the virtual "
                        "8-mesh: the TP collective budget",
            build=_build_decode_scan_tp2,
            donated=(1,),
            # GSPMD entry params are per-device shapes; dtype matching is
            # the shard-stable way to verify the cache donation survived
            alias_by_dtype=True,
            # 2 layers x (attention wo + ffn down) psums + the logits psum.
            # Anything beyond this set is a reshard the sharding annotations
            # never asked for.
            collectives={"all-reduce": 5},
            waivers={
                "collective:all-gather":
                    "sampling epilogue, not a cache reshard: top-k over the "
                    "vocab-sharded logits gathers [1,256] candidate scores "
                    "plus two [1,2] partial-result rows per step — bytes, "
                    "not the KV cache (first enforcing run, 2026-08)",
            },
        ),
        Contract(
            name="llm.paged_decode_step_s4",
            description="ContinuousBatcher PAGED pipelined decode step "
                        "(S=4, k=1, 8-token pages, oversubscribed 10-page "
                        "pool): the hot function of paged served decode",
            build=_build_paged_decode_step,
            donated=(1, 3, 4),
            forbid_dtypes=((_f32_pool_sig(), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.prefill_chunk_c8",
            description="chunked admission prefill (chunk=8 tokens into "
                        "the paged pool through a block-table row): the "
                        "scatter must update the pool in place",
            build=_build_prefill_chunk,
            donated=(1,),
            forbid_dtypes=((_f32_pool_sig(), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.verify_step_k4",
            description="speculative ngram draft+verify step over the "
                        "paged pool (S=4, K=4): ONE K+1-token target "
                        "forward per dispatched turn — the PR 8 hot "
                        "function. Zero host transfers; caches / next_pos "
                        "/ keys / hist donated (last_tok is not: its "
                        "buffer may alias the stacked token output the "
                        "host reads)",
            build=_build_verify_step_k4,
            donated=(1, 3, 4, 7),
            forbid_dtypes=((_f32_pool_sig(), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.verify_step_dense_k4",
            description="speculative ngram draft+verify step over the "
                        "dense slot cache (the A/B reference layout): "
                        "PAD_POS columns drop their writes instead of "
                        "redirecting to the trash page",
            build=_build_verify_step_dense_k4,
            donated=(1, 3, 4, 6),
            forbid_dtypes=((_f32_cache_sig(SLOTS), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.draft_verify_step_k4",
            description="draft-model spec step (S=4, K=4, dense): K+1 "
                        "sequential greedy draft forwards fused with the "
                        "single K+1-token target verify; BOTH caches "
                        "(target + draft) must donate through the program",
            build=_build_draft_verify_step_k4,
            donated=(1, 3, 4, 6, 9),
            forbid_dtypes=((_f32_cache_sig(SLOTS), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.lora_decode_step",
            description="batched-LoRA paged decode step (S=4, k=1, rank-2 "
                        "pool x 4 rows): the plain pipelined step plus one "
                        "gather+einsum pair per adapted q/o/FFN projection "
                        "— adapter id 0 is the zero-delta identity, so this "
                        "program serves base and adapted slots alike. Same "
                        "donation shape as the plain step; the pool/ids are "
                        "shared state and must not alias. Its cost budget "
                        "must sit within the plain step's tolerance band "
                        "(tests/test_adapters.py pins it): near-base-model "
                        "throughput is the design claim",
            build=_build_lora_decode_step,
            donated=(1, 3, 4),
            forbid_dtypes=((_f32_pool_sig(), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="llm.lora_verify_step",
            description="batched-LoRA speculative verify step (S=4, K=4, "
                        "paged): per-slot adapter deltas in the K+1-token "
                        "TARGET forward (ngram drafting stays base-model); "
                        "caches / next_pos / keys / hist donated like the "
                        "plain verify step, adapter pool/ids un-donated",
            build=_build_lora_verify_step,
            donated=(1, 3, 4, 7),
            forbid_dtypes=((_f32_pool_sig(), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="batcher.set_hist_row",
            description="speculative token-history row write at admission: "
                        "donated like the other per-slot state (the host "
                        "keeps no mirror of the history)",
            build=_build_set_hist_row,
            donated=(0,),
            collectives={},
        ),
        Contract(
            name="batcher.set_block_row",
            description="ContinuousBatcher block-table row update "
                        "(admission activate / slot release): donated so "
                        "the table never copies behind in-flight steps",
            build=_build_set_block_row,
            donated=(0,),
            collectives={},
        ),
        Contract(
            name="batcher.reset_pages",
            description="newly-allocated page position reset (PAD_POS "
                        "scatter across layers): the pool must be donated "
                        "through it, never copied per allocation",
            build=_build_reset_pages,
            donated=(0,),
            collectives={},
        ),
        Contract(
            name="disagg.import_pages",
            description="disaggregated prefill handoff, decode-side "
                        "import (PR 9): the worker's staged pages scatter "
                        "whole-pages into the slot pool through the "
                        "admission's block row — ZERO host transfers (the "
                        "KV moved device-to-device and must stay on "
                        "device), slot pool donated (the import updates in "
                        "place behind in-flight steps; the staged pool is "
                        "a dropped transient, NOT donated), bytes within "
                        "the committed budget",
            build=_build_handoff_import,
            donated=(0,),
            forbid_dtypes=((_f32_pool_sig(), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="batcher.cow_page_copy",
            description="radix prefix cache copy-on-write page copy "
                        "(PR 12): a slot continuing part-way into a "
                        "shared cached block copies that ONE page into "
                        "its own (values whole-page, position row masked "
                        "past the valid tokens) — pool donated so the "
                        "copy scatters in place, zero host transfers, "
                        "bytes budgeted at one page not a prefix gather",
            build=_build_cow_page_copy,
            donated=(0,),
            forbid_dtypes=((_f32_pool_sig(), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="disagg.prefix_export",
            description="radix prefix cache disaggregated export "
                        "(PR 12): cached-prefix pages gather into a "
                        "handoff-shaped bucket for the D2D ship to a "
                        "prefill worker (which then computes ONLY the "
                        "uncached suffix) — the pool is NOT donated (the "
                        "trie's pages stay live) and the cost budget "
                        "pins the bucket's bytes, never the pool's",
            build=_build_prefix_export,
            forbid_dtypes=((_f32_pool_sig(), F32_CACHE_WHY),),
            collectives={},
            cost=True,
        ),
        Contract(
            name="batcher.insert",
            description="ContinuousBatcher slot insert: the big slot cache "
                        "must be donated through the scatter",
            build=_build_batcher_insert,
            donated=(0,),
            collectives={},
        ),
        Contract(
            name="batcher.set_slot",
            description="ContinuousBatcher per-slot admission update of the "
                        "device-resident decode state",
            build=_build_batcher_set_slot,
            donated=(1, 2),
            collectives={},
        ),
        Contract(
            name="jaxserver.predict_b4",
            description="JAXServer jitted apply (tiny MLP checkpoint, "
                        "bucket=4): the generic predict hot path",
            build=_build_jaxserver_predict,
            collectives={},
            cost=True,
        ),
        Contract(
            name="ops.fused_norm",
            description="fused residual+RMSNorm ([8,2048] bf16): the decode "
                        "block epilogue",
            build=_build_fused_norm,
            # both outputs (residual sum, normed activation) must stay in
            # the model dtype — the f32 norm INTERNALS are the contract,
            # f32 OUTPUTS would double the block's activation traffic
            out_dtypes=((0, "bf16"), (1, "bf16")),
            collectives={},
            cost=True,
        ),
        Contract(
            name="ops.ring_attention_seq8",
            description="ring attention over the 8-way 'seq' mesh: one "
                        "rotating ppermute per buffer (k, v, positions)",
            build=_build_ring_attention,
            collectives={"collective-permute": 3},
            cost=True,
        ),
    ]
