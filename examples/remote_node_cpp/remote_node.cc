// A non-Python microservice implementing the Seldon wire contract — the
// role of the reference's nodejs wrapper (wrappers/s2i/nodejs/
// microservice.js:1-147): any language that can serve these routes can be
// a graph node. The engine reaches it through a unit's "endpoint" field
// (runtime/remote.py), no implementation required.
//
// Routes (REST):
//   GET  /live, /ready, /health/ping        -> 200
//   POST /predict, /api/v0.1/predictions    -> SeldonMessage JSON
//   POST /transform-input                   -> same contract
//
// The "user model" here doubles every value and names the features — enough
// to prove a C++ node joins a graph with full payload/meta semantics.
// Build:  g++ -O2 -std=c++17 remote_node.cc -o remote_node
// Run:    ./remote_node <port>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

// -- minimal JSON number-matrix extraction ---------------------------------
// Parses {"data": {"ndarray": [[...]]}} (or a flat list) without a JSON
// library: finds the "ndarray" key, then reads nested number rows. Good for
// the contract's numeric payloads; anything else answers 400.
bool parse_ndarray(const std::string& body, std::vector<std::vector<double>>& rows) {
  size_t key = body.find("\"ndarray\"");
  if (key == std::string::npos) return false;
  size_t p = body.find('[', key);
  if (p == std::string::npos) return false;
  size_t depth = 0;
  std::vector<double> cur;
  bool any_nested = false;
  std::string num;
  auto flush_num = [&]() {
    if (!num.empty()) {
      cur.push_back(atof(num.c_str()));
      num.clear();
    }
  };
  for (size_t i = p; i < body.size(); ++i) {
    char c = body[i];
    if (c == '[') {
      ++depth;
      if (depth == 2) any_nested = true;
      continue;
    }
    if (c == ']') {
      flush_num();
      if (depth == 2 || (depth == 1 && !any_nested)) {
        if (!cur.empty()) rows.push_back(cur);
        cur.clear();
      }
      if (--depth == 0) return !rows.empty();
      continue;
    }
    if (c == ',' || isspace((unsigned char)c)) {
      flush_num();
      continue;
    }
    if (isdigit((unsigned char)c) || c == '-' || c == '+' || c == '.' ||
        c == 'e' || c == 'E') {
      num.push_back(c);
      continue;
    }
    return false;  // strings/objects inside the array: not a numeric matrix
  }
  return false;
}

std::string predict_response(const std::vector<std::vector<double>>& rows) {
  // the "user model": y = 2x, names c0..cN — mirrors the nodejs example's
  // trivially-verifiable transform
  std::string out = "{\"data\": {\"names\": [";
  size_t cols = rows.empty() ? 0 : rows[0].size();
  for (size_t j = 0; j < cols; ++j) {
    if (j) out += ", ";
    out += "\"c" + std::to_string(j) + "\"";
  }
  out += "], \"ndarray\": [";
  char buf[64];
  for (size_t i = 0; i < rows.size(); ++i) {
    if (i) out += ", ";
    out += "[";
    for (size_t j = 0; j < rows[i].size(); ++j) {
      if (j) out += ", ";
      snprintf(buf, sizeof(buf), "%.12g", 2.0 * rows[i][j]);
      out += buf;
    }
    out += "]";
  }
  out += "]}}";
  return out;
}

void respond(int fd, int code, const char* text, const std::string& body,
             const char* ctype = "application/json") {
  char head[256];
  int n = snprintf(head, sizeof(head),
                   "HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
                   "Content-Length: %zu\r\nConnection: close\r\n\r\n",
                   code, text, ctype, body.size());
  (void)!write(fd, head, n);
  (void)!write(fd, body.data(), body.size());
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 9000;
  signal(SIGPIPE, SIG_IGN);
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)port);
  if (bind(srv, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(srv, 64) != 0) {
    perror("bind/listen");
    return 1;
  }
  fprintf(stderr, "remote_node listening on %d\n", port);
  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    std::string req;
    char buf[65536];
    // read until headers + declared body are in (Connection: close model)
    size_t content_len = 0, hdr_end = std::string::npos;
    for (;;) {
      ssize_t n = read(fd, buf, sizeof(buf));
      if (n <= 0) break;
      req.append(buf, (size_t)n);
      if (hdr_end == std::string::npos) {
        hdr_end = req.find("\r\n\r\n");
        if (hdr_end != std::string::npos) {
          size_t cl = req.find("Content-Length:");
          if (cl == std::string::npos) cl = req.find("content-length:");
          if (cl != std::string::npos && cl < hdr_end)
            content_len = strtoul(req.c_str() + cl + 15, nullptr, 10);
        }
      }
      if (hdr_end != std::string::npos &&
          req.size() >= hdr_end + 4 + content_len)
        break;
    }
    if (hdr_end == std::string::npos) {
      close(fd);
      continue;
    }
    bool is_get = req.rfind("GET ", 0) == 0;
    bool is_post = req.rfind("POST ", 0) == 0;
    std::string path = req.substr(is_get ? 4 : 5, req.find(' ', 5) - (is_get ? 4 : 5));
    std::string body = req.substr(hdr_end + 4);
    if (is_get && (path == "/live" || path == "/ready" || path == "/health/ping")) {
      respond(fd, 200, "OK", "{\"status\": \"ok\"}");
    } else if (is_post && (path == "/predict" || path == "/transform-input" ||
                           path == "/api/v0.1/predictions" ||
                           path == "/api/v1.0/predictions")) {
      std::vector<std::vector<double>> rows;
      if (parse_ndarray(body, rows)) {
        respond(fd, 200, "OK", predict_response(rows));
      } else {
        respond(fd, 400, "Bad Request",
                "{\"status\": {\"code\": 400, \"reason\": "
                "\"MICROSERVICE_BAD_DATA\", \"info\": "
                "\"expected data.ndarray of numbers\", \"status\": \"FAILURE\"}}");
      }
    } else if (is_post && path == "/send-feedback") {
      respond(fd, 200, "OK", "{\"meta\": {}}");
    } else {
      respond(fd, 404, "Not Found", "{\"status\": {\"code\": 404}}");
    }
    close(fd);
  }
}
