"""Headline benchmark: ResNet-50 bf16 serving throughput on one TPU chip.

This is the BASELINE.json north-star config ("ResNet-50 ... on v5e-8 at
>=8k img/s"); ``vs_baseline`` divides by the per-chip share of that target
(1000 img/s). Methodology is MLPerf-offline-style batched serving: the input
pool is staged to the device once, a ``lax.scan`` runs `iters` jitted bf16
forward passes back-to-back (each iteration data-depends on the previous so
XLA can neither hoist nor overlap them away), and one host sync ends the
round. This amortises the host<->device link, which on this harness is a
tunnel with ~75 ms RTT and ~120 MB/s bandwidth — per-batch host syncs would
measure the tunnel, not the serving stack.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``--mode llm`` instead benchmarks autoregressive decode tokens/s through
LLMServer's compiled prefill+scan-decode path on a ~0.7B-param llama-style
config (the single-chip share of the BASELINE.json Llama-2-7B stretch
target); the serving report lives in benchmarks/report_llm_decode.json.
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial

import numpy as np

PER_CHIP_BASELINE_IMGS = 1000.0  # 8000 img/s target / 8 chips (BASELINE.json)


def main_llm() -> None:
    import jax

    from seldon_core_tpu.servers.llmserver import LLMServer

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    # ~0.7B params bf16 (~1.4GB): fits one v5e chip with cache headroom
    kwargs = (
        dict(vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
             n_kv_heads=16, ffn_dim=5504, max_seq_len=2048)
        if on_tpu
        else dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                  n_kv_heads=2, ffn_dim=128, max_seq_len=128)
    )
    batch = 8 if on_tpu else 2
    max_new = 128 if on_tpu else 8
    plen = 128 if on_tpu else 16

    server = LLMServer(
        model="transformer", model_kwargs=kwargs, init_random=True,
        max_new_tokens=max_new, len_buckets=(plen,), batch_buckets=(batch,),
        temperature=0.0, eos_id=-1,  # never stops: steady-state decode rate
    )
    server.load()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, kwargs["vocab_size"] - 1, size=plen).tolist()
               for _ in range(batch)]

    server.generate(prompts, max_new_tokens=max_new)  # compile + warm
    best = float("inf")
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        out = server.generate(prompts, max_new_tokens=max_new)
        best = min(best, time.perf_counter() - t0)
    n_tokens = sum(len(t) for t in out["tokens"])
    toks_per_s = n_tokens / best
    print(
        json.dumps(
            {
                "metric": f"llm-decode-0.7b-b{batch}-1chip[{dev.platform}]",
                "value": round(toks_per_s, 2),
                "unit": "tok/s",
                "vs_baseline": 0.0,  # no reference LLM-serving number exists
            }
        )
    )


def main() -> None:
    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models import get_model

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    # b128 measured fastest on-chip (12,163 img/s vs 11,541 at b256 —
    # benchmarks/tpu_sweep_results.jsonl latency sweep) and serves a 10.5ms
    # batch latency instead of 22ms
    batch = 128 if on_tpu else 8
    iters = 50 if on_tpu else 2

    # Inference-optimized serving config (benchmarks/MFU_NOTES.md):
    # BN folded into the convs (fold_batchnorm — bit-exact, removes every
    # stats read + affine chain) and the input pool staged as bf16 (the
    # model computes in bf16 anyway; halves the first conv's HBM read).
    from seldon_core_tpu.models.resnet import fold_batchnorm

    model = get_model("resnet50", fused=True)
    init_model = get_model("resnet50")
    x0 = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = fold_batchnorm(jax.jit(init_model.init)(jax.random.PRNGKey(0), x0))

    @partial(jax.jit, static_argnums=2)
    def serve_loop(variables, pool, iters):
        def body(x, _):
            logits = model.apply(variables, x, train=False)
            x = x * (1.0 + 1e-12 * jnp.mean(logits).astype(x.dtype))
            return x, jnp.mean(logits)

        _, means = jax.lax.scan(body, pool, None, length=iters)
        return means

    pool = jax.device_put(
        jnp.asarray(
            np.random.default_rng(0).standard_normal((batch, 224, 224, 3), dtype=np.float32)
        ).astype(jnp.bfloat16),
        dev,
    )

    # Pinned methodology (benchmarks/MFU_NOTES.md round-5 log): 1 compile
    # round + 2 discarded warmup rounds, then 7 timed rounds; report the
    # MEDIAN with its spread (max-min over the timed rounds, as % of the
    # median). Chip sessions vary 9-16% day to day; the median-with-spread
    # is the quotable number, best-of-N is not.
    np.asarray(serve_loop(variables, pool, iters))  # compile
    warmup, repeats = (2, 7) if on_tpu else (0, 1)
    for _ in range(warmup):
        np.asarray(serve_loop(variables, pool, iters))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(serve_loop(variables, pool, iters))  # host sync ends the round
        times.append(time.perf_counter() - t0)

    med = float(np.median(times))
    imgs_per_s = batch * iters / med
    spread_pct = 100.0 * (max(times) - min(times)) / med
    print(
        json.dumps(
            {
                "metric": f"resnet50-bf16-b{batch}-serve-1chip[{dev.platform}]",
                "value": round(imgs_per_s, 2),
                "unit": "img/s",
                "vs_baseline": round(imgs_per_s / PER_CHIP_BASELINE_IMGS, 4),
                "method": f"median of {repeats} rounds after {warmup} warmup",
                "spread_pct": round(spread_pct, 1),
            }
        )
    )


def _probe_tpu(timeout_s: float = 120.0) -> bool:
    """Is the TPU backend actually reachable? The axon tunnel can wedge so
    hard that jax.devices() never returns (see benchmarks/MFU_NOTES.md) —
    probe in a subprocess so a dead tunnel degrades to an honestly-labeled
    CPU number instead of hanging the whole bench."""
    import os
    import subprocess
    import sys

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        return False  # explicitly CPU-forced: don't pay a probe backend init
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=timeout_s, text=True,
        )
        return out.returncode == 0 and "tpu" in out.stdout
    except subprocess.TimeoutExpired:
        return False


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="resnet", choices=["resnet", "llm"])
    args = ap.parse_args()
    if not _probe_tpu():
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.mode == "llm":
        main_llm()
    else:
        main()
